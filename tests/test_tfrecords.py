"""TFRecord datasource (ref: read_api.py read_tfrecords + tfrecords
datasource): TF-compatible framing (masked crc32c) and tf.train.Example
protos, implemented without TensorFlow."""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as data
from ray_tpu.data.tfrecords import (
    crc32c,
    example_to_row,
    read_records,
    row_to_example,
    write_records,
)


def test_crc32c_known_vectors():
    from ray_tpu.data.tfrecords import _crc32c_py

    # Published CRC-32C (Castagnoli) test vectors, for BOTH the active
    # implementation (C extension when present) and the pure fallback.
    for fn in (crc32c, _crc32c_py):
        assert fn(b"") == 0x00000000
        assert fn(b"a") == 0xC1D04330
        assert fn(b"123456789") == 0xE3069283
        assert fn(b"\x00" * 32) == 0x8A9136AA


def test_record_framing_roundtrip(tmp_path):
    path = str(tmp_path / "r.tfrecords")
    records = [b"alpha", b"", b"x" * 10_000]
    assert write_records(path, records) == 3
    assert list(read_records(path)) == records
    # Corruption detection: flip one payload byte.
    blob = bytearray(open(path, "rb").read())
    blob[12] ^= 0xFF  # first byte of record 0's data
    open(path, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="crc"):
        list(read_records(path))


def test_example_proto_roundtrip():
    row = {"name": b"abc", "score": 1.5, "count": 7,
           "vec": [1.0, 2.0, 3.5], "ids": [1, 2, 3]}
    back = example_to_row(row_to_example(row))
    assert back["name"] == b"abc"
    assert back["score"] == pytest.approx(1.5)
    assert back["count"] == 7
    assert back["vec"] == pytest.approx([1.0, 2.0, 3.5])
    assert back["ids"] == [1, 2, 3]


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_ragged_features_and_nulls(rt, tmp_path):
    """Variable-length features (standard sparse usage) become list
    columns; None cells write as empty features and read back as []."""
    from ray_tpu.data.tfrecords import examples_to_block

    rows = [{"ids": [1, 2], "tag": b"a"},
            {"ids": [3, 4, 5], "tag": None},
            {"ids": 9, "tag": b"c"}]  # scalar mixed with lists
    blk = examples_to_block(row_to_example(r) for r in rows)
    got = sorted((list(x) for x in blk.column("ids").to_pylist()),
                 key=len)
    assert got == [[9], [1, 2], [3, 4, 5]]
    tags = blk.column("tag").to_pylist()
    assert sorted(t if isinstance(t, bytes) else bytes(t or b"")
                  for t in [x if not isinstance(x, list) else
                            (x[0] if x else b"") for x in tags]) \
        == [b"", b"a", b"c"]

    path = str(tmp_path / "ragged")
    data.from_items(rows).write_tfrecords(path)
    back = data.read_tfrecords(path).take_all()
    assert len(back) == 3


def test_tf_naming_convention_and_extensionless(rt, tmp_path):
    """TF-style *.tfrecord names and extension-less shards both read."""
    d = tmp_path / "tfdir"
    d.mkdir()
    recs = [row_to_example({"v": i}) for i in range(5)]
    write_records(str(d / "train-00000-of-00001.tfrecord"), recs[:3])
    write_records(str(d / "train-00001"), recs[3:])
    # .tfrecord matched first; extension-less fallback only when nothing
    # with a tfrecord suffix exists.
    assert len(data.read_tfrecords(str(d)).take_all()) == 3
    d2 = tmp_path / "bare"
    d2.mkdir()
    write_records(str(d2 / "shard-0"), recs)
    assert len(data.read_tfrecords(str(d2)).take_all()) == 5
    # ADVICE r4: a stray non-TFRecord file (README/_SUCCESS marker) must be
    # skipped by the extension-less fallback, not fail later with a
    # confusing length-crc error.
    (d2 / "_SUCCESS").write_text("")
    (d2 / "README.md").write_text("this is not a tfrecord\n" * 4)
    assert len(data.read_tfrecords(str(d2)).take_all()) == 5
    d3 = tmp_path / "junk_only"
    d3.mkdir()
    (d3 / "notes.txt").write_text("nothing here frames as a record")
    import pytest as _pytest
    with _pytest.raises(FileNotFoundError, match="frame as TFRecords"):
        data.read_tfrecords(str(d3))


def test_dataset_write_read_roundtrip(rt, tmp_path):
    rows = [{"id": i, "w": float(i) * 0.5, "tag": f"t{i}".encode()}
            for i in range(100)]
    ds = data.from_items(rows).repartition(4)
    path = str(tmp_path / "out")
    ds.write_tfrecords(path)
    import glob

    files = glob.glob(path + "/*.tfrecords")
    assert len(files) == 4
    back = data.read_tfrecords(path)
    got = sorted(back.take_all(), key=lambda r: r["id"])
    assert len(got) == 100
    assert got[10]["id"] == 10
    assert got[10]["w"] == pytest.approx(5.0)
    assert bytes(got[10]["tag"]) == b"t10"
