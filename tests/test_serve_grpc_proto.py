"""Proto-level gRPC interop with the REFERENCE serve schema (VERDICT r3
missing #8): message classes are built dynamically from the reference's
serve.proto field layout (src/ray/protobuf/serve.proto:309-334), so these
tests prove a client compiled against the reference's stubs gets wire-
compatible bytes from our proxy — builtins under the reference's
fully-qualified service name, and user proto payloads passing through the
generic handler intact."""

import pytest

import ray_tpu
from ray_tpu import serve


def _reference_messages():
    """Build the reference's message classes from its schema (grpcio-tools
    is not in the image; the descriptor_pb2 route needs only protobuf)."""
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    f = descriptor_pb2.FileDescriptorProto()
    f.name = "ref_serve_api.proto"
    f.package = "ray.serve"
    f.syntax = "proto3"

    def add_msg(name, fields):
        m = f.message_type.add()
        m.name = name
        for fname, number, ftype, label in fields:
            fld = m.field.add()
            fld.name = fname
            fld.number = number
            fld.type = ftype
            fld.label = label

    FT = descriptor_pb2.FieldDescriptorProto
    # ref serve.proto:309 ListApplicationsResponse{repeated string
    # application_names = 1}; :315 HealthzResponse{string message = 1};
    # :325 UserDefinedMessage{string name=1; string foo=2; int64 num=3};
    # :331 UserDefinedResponse{string greeting=1; int64 num_x2=2}.
    add_msg("ListApplicationsResponse",
            [("application_names", 1, FT.TYPE_STRING, FT.LABEL_REPEATED)])
    add_msg("HealthzResponse",
            [("message", 1, FT.TYPE_STRING, FT.LABEL_OPTIONAL)])
    add_msg("UserDefinedMessage",
            [("name", 1, FT.TYPE_STRING, FT.LABEL_OPTIONAL),
             ("foo", 2, FT.TYPE_STRING, FT.LABEL_OPTIONAL),
             ("num", 3, FT.TYPE_INT64, FT.LABEL_OPTIONAL)])
    add_msg("UserDefinedResponse",
            [("greeting", 1, FT.TYPE_STRING, FT.LABEL_OPTIONAL),
             ("num_x2", 2, FT.TYPE_INT64, FT.LABEL_OPTIONAL)])
    pool.Add(f)

    def cls(name):
        return message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"ray.serve.{name}"))

    return {n: cls(n) for n in ("ListApplicationsResponse",
                                "HealthzResponse", "UserDefinedMessage",
                                "UserDefinedResponse")}


@pytest.fixture
def grpc_serve():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    serve.start(http_options={"port": 0}, grpc_options={"port": 0})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _grpc_addr():
    from ray_tpu.serve.api import _state

    return _state["grpc_proxy"].address


def test_reference_api_service_wire_compat(grpc_serve):
    import grpc

    msgs = _reference_messages()

    @serve.deployment
    class App:
        def __call__(self, request):
            return b"ok"

    serve.run(App.bind(), name="proto_app", route_prefix=None)
    channel = grpc.insecure_channel(_grpc_addr())

    healthz = channel.unary_unary(
        "/ray.serve.RayServeAPIService/Healthz",
        request_serializer=lambda b: b,
        response_deserializer=msgs["HealthzResponse"].FromString)
    resp = healthz(b"", timeout=30)
    assert resp.message == "success"

    list_apps = channel.unary_unary(
        "/ray.serve.RayServeAPIService/ListApplications",
        request_serializer=lambda b: b,
        response_deserializer=msgs["ListApplicationsResponse"].FromString)
    import time

    deadline = time.time() + 20  # route-table long-poll propagation
    names = []
    while time.time() < deadline:
        names = list(list_apps(b"", timeout=30).application_names)
        if "proto_app" in names:
            break
        time.sleep(0.2)
    assert "proto_app" in names, names
    channel.close()


def test_user_proto_payload_roundtrip(grpc_serve):
    """A user proto message (the reference's own test schema) crosses the
    generic handler intact in both directions — the ingress parses the
    request fields and replies with a reference-schema response."""
    import grpc

    msgs = _reference_messages()
    req_cls, resp_cls = (msgs["UserDefinedMessage"],
                         msgs["UserDefinedResponse"])

    # The ingress parses the reference request schema BY WIRE FORMAT and
    # emits reference response bytes (defined in the replica, where only
    # protobuf — present in the image — is needed).
    @serve.deployment
    class ProtoEcho:
        def __call__(self, request):
            from tests.test_serve_grpc_proto import _reference_messages

            m = _reference_messages()
            req = m["UserDefinedMessage"].FromString(request.payload)
            out = m["UserDefinedResponse"](
                greeting=f"Hello {req.name} from {req.foo}",
                num_x2=req.num * 2)
            return out.SerializeToString()

    serve.run(ProtoEcho.bind(), name="proto_echo", route_prefix=None)
    channel = grpc.insecure_channel(_grpc_addr())
    call = channel.unary_unary(
        "/userdefined.UserDefinedService/__call__",
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString)
    resp = call(req_cls(name="world", foo="bar", num=21), timeout=60,
                metadata=(("application", "proto_echo"),))
    assert resp.greeting == "Hello world from bar"
    assert resp.num_x2 == 42
    channel.close()
