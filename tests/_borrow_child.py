"""Borrower process for the cross-node borrowing-protocol test.

Reads a base64-pickled ObjectRef from argv, materializes it (registering a
borrow with the owner — the parent process), pulls the value, prints GOT,
then holds the ref until stdin closes; shutdown releases the borrow.
"""

import base64
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import ray_tpu  # noqa: E402
from ray_tpu._private import serialization  # noqa: E402


def main() -> None:
    ray_tpu.init()
    ref = serialization.loads(base64.b64decode(sys.argv[1]))
    value = ray_tpu.get(ref, timeout=30)
    print(f"GOT {int(value.sum())}", flush=True)
    sys.stdin.read()  # parent closes stdin when it wants the release
    del ref
    ray_tpu.shutdown()  # release_all returns the borrow
    print("RELEASED", flush=True)


if __name__ == "__main__":
    main()
