"""_node_details semaphore hygiene: a node wedged inside node_info (e.g.
conn.send on a full pipe — the one unbounded block in that stack) must not
eat a _SNAP_BUDGET slot forever, and later rounds must not stack more
threads behind the same wedged node."""

import threading

from ray_tpu._private import metrics_agent as ma


class _FakeServer:
    def __init__(self, wedged):
        self.wedged = wedged
        self.unblock = threading.Event()
        self.calls = []

    def node_info(self, rn, timeout=3.0, detail="full"):
        self.calls.append(rn)
        if rn in self.wedged:
            self.unblock.wait()  # hangs until the test releases it
        return {"node": rn}


class _FakeRuntime:
    pass


def _drain_budget():
    got = 0
    while ma._SNAP_BUDGET.acquire(blocking=False):
        got += 1
    for _ in range(got):
        ma._SNAP_BUDGET.release()
    return got


def test_wedged_node_info_releases_budget_and_is_skipped(monkeypatch):
    monkeypatch.setattr(ma, "_SNAP_DEADLINE_S", 0.3)
    rt = _FakeRuntime()
    rt.node_server = srv = _FakeServer(wedged={"bad"})
    remote = {"bad": "bad", "good": "good"}

    baseline = _drain_budget()
    assert baseline == 8, "another test leaked snapshot budget slots"

    try:
        details = ma._node_details(rt, remote)
        assert details.get("good") == {"node": "good"}
        assert "bad" not in details  # wedged past the deadline: omitted
        # The deadline sweep reclaimed the wedged fetch's slot.
        assert _drain_budget() == baseline

        # Round 2 (cache cleared): the wedged node is skipped outright —
        # no second thread queues behind it — and the budget stays intact.
        with ma._SNAP_LOCK:
            ma._SNAP_CACHE.pop(rt, None)
        details = ma._node_details(rt, remote)
        assert "wedged" in details["bad"]["error"]
        assert details["good"] == {"node": "good"}
        assert srv.calls.count("bad") == 1
        assert _drain_budget() == baseline
    finally:
        srv.unblock.set()

    # Once the wedged fetch finally returns, its late release is a no-op
    # (the deadline sweep already released) and the node is fetchable again.
    deadline = threading.Event()
    for _ in range(100):
        with ma._SNAP_LOCK:
            free = "bad" not in ma._SNAP_INFLIGHT.get(rt, set())
        if free:
            break
        deadline.wait(0.05)
    assert _drain_budget() == baseline
    with ma._SNAP_LOCK:
        ma._SNAP_CACHE.pop(rt, None)
    details = ma._node_details(rt, remote)
    assert details["bad"] == {"node": "bad"}
