"""Scheduling policy + placement group tests
(ref model: src/ray/raylet/scheduling/scheduling_policy_test.cc,
python/ray/tests/test_placement_group.py)."""

import time

import pytest

import ray_tpu
from ray_tpu._private.scheduling import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadStrategy,
)
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)


def test_spread_across_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return True

    refs = [
        where.options(scheduling_strategy=SpreadStrategy()).remote() for _ in range(8)
    ]
    assert all(ray_tpu.get(refs))


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    target = cluster.add_node(num_cpus=2, resources={"special": 1})

    @ray_tpu.remote(num_cpus=1)
    def f():
        return 1

    ref = f.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=str(target))
    ).remote()
    assert ray_tpu.get(ref) == 1


def test_node_labels(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, labels={"zone": "us-central2-b"})

    @ray_tpu.remote(num_cpus=1)
    def f():
        return "labeled"

    ref = f.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(hard={"zone": "us-central2-b"})
    ).remote()
    assert ray_tpu.get(ref) == "labeled"


def test_custom_resources(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"TPU": 4})

    @ray_tpu.remote(num_tpus=2)
    def tpu_task():
        return "on tpu node"

    assert ray_tpu.get(tpu_task.remote()) == "on tpu node"


def test_pg_pack(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(5)
    nodes = pg.bundle_node_ids()
    assert nodes[0] == nodes[1]  # PACK puts bundles together


def test_pg_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(5)
    nodes = pg.bundle_node_ids()
    assert len(set(nodes)) == 3


def test_pg_strict_pack_ici_slice(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, labels={"ici-slice": "slice-a"})
    big = cluster.add_node(num_cpus=8, labels={"ici-slice": "slice-b"})
    pg = placement_group([{"CPU": 2}] * 3, strategy="STRICT_PACK")
    assert pg.wait(5)
    assert set(pg.bundle_node_ids()) == {str(big)}


def test_pg_task_scheduling(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(5)

    @ray_tpu.remote(num_cpus=1)
    def inside():
        return "in bundle"

    ref = inside.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    ).remote()
    assert ray_tpu.get(ref) == "in bundle"


def test_pg_pending_until_node_added(ray_start_cluster):
    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 16}], strategy="PACK")
    assert not pg.wait(0.2)
    cluster.add_node(num_cpus=16)
    assert pg.wait(5)


def test_pg_remove_releases_resources(ray_start_cluster):
    cluster = ray_start_cluster
    node = cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 4}], strategy="PACK")
    assert pg.wait(5)
    avail_before = ray_tpu.available_resources().get("CPU", 0)
    remove_placement_group(pg)
    time.sleep(0.1)
    assert ray_tpu.available_resources().get("CPU", 0) == avail_before + 4
    assert str(pg.id) not in placement_group_table() or placement_group_table() == {}


def test_actor_in_pg(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=4)
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(5)

    @ray_tpu.remote(num_cpus=1)
    class Worker:
        def ping(self):
            return "pong"

    a = Worker.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)
    ).remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
