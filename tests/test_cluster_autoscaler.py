"""Signal-driven cluster autoscaler (ISSUE 20): policy determinism,
postmortem quarantine, fault-gated actuation, Monitor shutdown, drain
semantics, provider terminate idempotency and locality-aware claiming.

Policy tests drive ``ClusterAutoscaler.tick(signals=...)`` with synthetic
:class:`ClusterSignals` snapshots (the layer is keyed entirely on the
snapshot's ``now``, so no sleeps) against the REAL reconciler +
scheduler, with only the node provider simulated — the bench_cluster.py
harness, miniaturized.
"""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu._private import fault_injection
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.scheduling import ClusterScheduler, DefaultStrategy
from ray_tpu.autoscaler.autoscaler import (Autoscaler, AutoscalerConfig,
                                           Monitor, NodeTypeConfig)
from ray_tpu.autoscaler.instance_manager import InstanceState
from ray_tpu.autoscaler.node_provider import (FakeNodeProvider, NodeProvider,
                                              SubprocessNodeProvider,
                                              TPUPodProvider)
from ray_tpu.autoscaler.policy import (ClusterAutoscaler, ClusterPolicyConfig,
                                       QuarantineTracker)
from ray_tpu.autoscaler.signals import ClusterSignals, SignalCollector
from ray_tpu.train.elastic import SampleLedger


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _wait(pred, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {msg}")


class SimProvider(NodeProvider):
    """Instant in-memory cloud over a real scheduler (bench_cluster.py)."""

    def __init__(self, scheduler):
        self.scheduler = scheduler
        self._nodes = {}
        self._n = 0
        self.created = 0

    def create_node(self, node_type, resources, labels):
        node_id = self.scheduler.add_node(
            dict(resources), {**labels, "node-type": node_type})
        self._n += 1
        self.created += 1
        pid = f"sim-{self._n}"
        self._nodes[pid] = node_id
        return pid

    def terminate_node(self, pid):
        node_id = self._nodes.pop(pid, None)  # idempotent by contract
        if node_id is not None:
            self.scheduler.remove_node(node_id)

    def non_terminated_nodes(self):
        return list(self._nodes)

    def scheduler_node_id(self, pid):
        return self._nodes.get(pid)

    def kill(self, pid):
        self.terminate_node(pid)


def _mk(node_types, policy):
    scheduler = ClusterScheduler()
    provider = SimProvider(scheduler)
    storage = tempfile.NamedTemporaryFile(suffix=".json", delete=False).name
    os.unlink(storage)
    asc = Autoscaler(
        AutoscalerConfig(node_types=node_types, idle_timeout_s=1e9,
                         cluster_name="test-cluster-policy"),
        provider, scheduler=scheduler, storage_path=storage)
    return ClusterAutoscaler(asc, policy), asc, provider, scheduler


def _serve_policy(**kw):
    base = dict(serve_qps_per_node=100.0, upscale_delay_s=5.0,
                upscale_cooldown_s=10.0, downscale_delay_s=60.0,
                downscale_cooldown_s=60.0)
    base.update(kw)
    return ClusterPolicyConfig(**base)


# ---------------------------------------------------------------- policy
def test_upscale_waits_for_hysteresis_delay():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, max_workers=10)},
        _serve_policy())
    sig = lambda t, r: ClusterSignals(now=float(t), serve_request_rate=r)
    ca.tick(signals=sig(0, 500.0))  # above target, delay not yet served
    assert asc.target_counts.get("serve") is None
    assert provider.created == 0
    ca.tick(signals=sig(2, 500.0))  # still inside the 5s delay
    assert provider.created == 0
    ca.tick(signals=sig(6, 500.0))  # delay served -> actuate
    assert asc.target_counts["serve"] == 5
    assert len(provider.non_terminated_nodes()) == 5
    # Desired is deterministic from the snapshot: ceil(500/100) = 5.
    assert asc.im.active_counts()["serve"] == 5


def test_burn_bypasses_delay_but_not_cooldown():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, min_workers=2,
                                 max_workers=10)},
        _serve_policy())
    ca.tick(signals=ClusterSignals(now=0.0))  # min_workers floor
    assert asc.im.active_counts()["serve"] == 2
    ca.tick(signals=ClusterSignals(now=1.0, slo_burn_alerting=True,
                                   slo_burn_quiet=False))
    # Burn skipped the 5s upscale delay: 2 -> max(3, ceil(2*1.5)) = 3.
    assert asc.target_counts["serve"] == 3
    ca.tick(signals=ClusterSignals(now=2.0, slo_burn_alerting=True,
                                   slo_burn_quiet=False))
    # ...but never the cooldown (10s): target unchanged one tick later.
    assert asc.target_counts["serve"] == 3


def test_scale_down_steps_one_node_per_decision():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, min_workers=1,
                                 max_workers=10)},
        _serve_policy())
    sig = lambda t, r: ClusterSignals(now=float(t), serve_request_rate=r)
    ca.tick(signals=sig(0, 500.0))
    ca.tick(signals=sig(6, 500.0))
    assert asc.im.active_counts()["serve"] == 5
    ca.tick(signals=sig(7, 500.0))  # instances reach RUNNING
    ca.tick(signals=sig(100, 50.0))  # below: starts the downscale clock
    assert asc.target_counts["serve"] == 5
    ca.tick(signals=sig(161, 50.0))  # 60s delay served
    # One step down per decision, and the idle node over target is
    # released in the SAME pass — no idle_timeout_s wait (1e9 here).
    assert asc.target_counts["serve"] == 4
    assert asc.im.active_counts()["serve"] == 4
    ca.tick(signals=sig(170, 50.0))  # inside downscale cooldown
    assert asc.target_counts["serve"] == 4
    ca.tick(signals=sig(231, 50.0))  # fresh 60s delay + cooldown served
    assert asc.target_counts["serve"] == 3


def test_protected_type_holds_scale_down_while_burn_not_quiet():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, min_workers=1,
                                 max_workers=10)},
        _serve_policy())
    sig = lambda t, r, quiet: ClusterSignals(
        now=float(t), serve_request_rate=r, slo_burn_quiet=quiet)
    ca.tick(signals=sig(0, 500.0, True))
    ca.tick(signals=sig(6, 500.0, True))
    assert asc.target_counts["serve"] == 5
    # Load drops but an SLO window is still burning: protected capacity
    # must not come down, no matter how long the low signal persists.
    for t in (100, 200, 300, 400):
        ca.tick(signals=sig(t, 50.0, False))
    assert asc.target_counts["serve"] == 5
    assert ca.last_decisions[0].reason == "hold_burn_not_quiet"
    # Quiet again: the downscale clock starts fresh from here.
    ca.tick(signals=sig(500, 50.0, True))
    assert asc.target_counts["serve"] == 5
    ca.tick(signals=sig(561, 50.0, True))
    assert asc.target_counts["serve"] == 4


def test_train_signals_route_to_preemptible_types_only():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, max_workers=10),
         "train": NodeTypeConfig(resources={"CPU": 16.0}, max_workers=10,
                                 preemptible=True)},
        _serve_policy(shards_per_node=10.0, upscale_delay_s=0.0))
    ca.tick(signals=ClusterSignals(now=0.0, pending_ingest_shards=35))
    # ceil(35/10) = 4 train nodes; the serve type saw nothing.
    assert asc.target_counts.get("train") == 4
    assert "serve" not in asc.target_counts
    counts = asc.im.active_counts()
    assert counts.get("train") == 4 and "serve" not in counts
    # And the launched capacity is labeled preemptible for the scheduler.
    sched_nodes = [provider.scheduler.get_node(provider.scheduler_node_id(p))
                   for p in provider.non_terminated_nodes()]
    assert all(n.labels.get("preemptible") == "true" for n in sched_nodes)
    # Serve rate drives only the protected type.
    ca.tick(signals=ClusterSignals(now=20.0, serve_request_rate=250.0,
                                   pending_ingest_shards=35))
    assert asc.target_counts["serve"] == 3
    assert asc.target_counts["train"] == 4


def test_data_starved_fraction_adds_one_preemptible_node():
    ca, asc, provider, _ = _mk(
        {"train": NodeTypeConfig(resources={"CPU": 16.0}, min_workers=2,
                                 max_workers=10, preemptible=True)},
        _serve_policy(upscale_delay_s=0.0, upscale_cooldown_s=0.0))
    ca.tick(signals=ClusterSignals(now=0.0))
    assert asc.im.active_counts()["train"] == 2
    ca.tick(signals=ClusterSignals(now=1.0,
                                   train_data_starved_fraction=0.5))
    assert asc.target_counts["train"] == 3  # active + 1


def test_signal_desired_clamps_to_type_caps():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, min_workers=2,
                                 max_workers=4)},
        _serve_policy(upscale_delay_s=0.0))
    ca.tick(signals=ClusterSignals(now=0.0, serve_request_rate=5000.0))
    assert asc.target_counts["serve"] == 4  # ceil(50) clamped to max
    assert asc.im.active_counts()["serve"] == 4


# ------------------------------------------------------------ quarantine
def test_quarantine_tracker_counts_fresh_ts_not_ids():
    tr = QuarantineTracker(threshold=3, window_s=600.0)
    pm = lambda ts: [{"id": "77-actor_death", "ts": ts,
                      "reason": "actor_death", "node": "n1"}]
    assert tr.observe(pm(1.0), now=1.0) == []
    # Same (id, ts) seen again — the dump file unchanged — is NOT a new
    # postmortem; only a fresh ts (the crash loop overwrote its dump) is.
    assert tr.observe(pm(1.0), now=2.0) == []
    assert tr.observe(pm(2.0), now=2.0) == []
    assert tr.observe(pm(3.0), now=3.0) == [("n1", "actor_death")]
    # Already quarantined: further postmortems produce no duplicate.
    assert tr.observe(pm(4.0), now=4.0) == []
    assert tr.quarantined == {"n1": "actor_death"}


def test_quarantine_tracker_window_prunes_old_events():
    tr = QuarantineTracker(threshold=3, window_s=10.0)
    rows = [{"id": f"{i}-crash", "ts": float(i), "reason": "crash",
             "node": "n1"} for i in range(3)]
    assert tr.observe([rows[0]], now=0.0) == []
    assert tr.observe([rows[1]], now=1.0) == []
    # Third event lands after the first fell out of the 10s window.
    assert tr.observe([rows[2]], now=20.0) == []
    assert tr.quarantined == {}


def test_crash_loop_node_quarantined_within_three_and_never_refilled():
    ca, asc, provider, scheduler = _mk(
        {"train": NodeTypeConfig(resources={"CPU": 16.0}, min_workers=3,
                                 max_workers=3, preemptible=True)},
        _serve_policy())
    for t in (0, 1, 2):  # warm up to 3 RUNNING nodes
        ca.tick(signals=ClusterSignals(now=float(t)))
    assert asc.im.active_counts()["train"] == 3
    victim = str(next(iter(asc.im.instances(
        InstanceState.RUNNING))).scheduler_node_id)
    pm = lambda t: [{"id": "4242-actor_death", "ts": float(t),
                     "reason": "actor_death", "node": victim}]
    ca.tick(signals=ClusterSignals(now=10.0, postmortems=pm(10)))
    ca.tick(signals=ClusterSignals(now=11.0, postmortems=pm(11)))
    assert victim not in ca.quarantine.quarantined  # only 2 so far
    out = ca.tick(signals=ClusterSignals(now=12.0, postmortems=pm(12)))
    assert out["quarantined"] == [victim]
    # The slot is retired for good: caps shrunk, node terminated, and the
    # min_workers floor can never relaunch into the crash loop.
    assert asc.config.node_types["train"].max_workers == 2
    assert asc.config.node_types["train"].min_workers == 2
    for t in range(13, 33):
        ca.tick(signals=ClusterSignals(now=float(t)))
    assert asc.im.active_counts()["train"] == 2
    live = {str(provider.scheduler_node_id(p))
            for p in provider.non_terminated_nodes()}
    assert victim not in live


def test_quarantine_drains_before_terminating(monkeypatch):
    """The drain lands in the scheduler before the instance is torn down,
    so in-flight leases finish but nothing NEW places on the node."""
    ca, asc, provider, scheduler = _mk(
        {"train": NodeTypeConfig(resources={"CPU": 16.0}, min_workers=2,
                                 max_workers=2, preemptible=True)},
        _serve_policy())
    ca.tick(signals=ClusterSignals(now=0.0))
    ca.tick(signals=ClusterSignals(now=1.0))
    victim = str(next(iter(asc.im.instances(
        InstanceState.RUNNING))).scheduler_node_id)
    drained = []
    orig = scheduler.set_node_draining
    monkeypatch.setattr(
        scheduler, "set_node_draining",
        lambda node, draining=True: drained.append((node, draining))
        or orig(node, draining))
    pm = lambda t: [{"id": "1-crash", "ts": float(t), "reason": "crash",
                     "node": victim}]
    for t in (10, 11, 12):
        ca.tick(signals=ClusterSignals(now=float(t), postmortems=pm(t)))
    assert (victim, True) in drained


# ------------------------------------------------------ fault injection
def test_injected_actuation_failure_leaves_target_unchanged():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, max_workers=10)},
        _serve_policy(upscale_delay_s=0.0))
    old = GLOBAL_CONFIG.testing_rpc_failure
    GLOBAL_CONFIG.testing_rpc_failure = "cluster_autoscale=1.0"
    fault_injection.reset_injector()
    try:
        for t in range(5):
            ca.tick(signals=ClusterSignals(now=float(t),
                                           serve_request_rate=800.0))
        assert "serve" not in asc.target_counts
        assert provider.created == 0
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = old
        fault_injection.reset_injector()
    # Fault cleared: the same signal actuates on the next tick.
    ca.tick(signals=ClusterSignals(now=10.0, serve_request_rate=800.0))
    assert asc.target_counts["serve"] == 8


def test_injected_quarantine_failure_retries_next_postmortem():
    ca, asc, provider, _ = _mk(
        {"train": NodeTypeConfig(resources={"CPU": 16.0}, min_workers=2,
                                 max_workers=2, preemptible=True)},
        _serve_policy())
    ca.tick(signals=ClusterSignals(now=0.0))
    ca.tick(signals=ClusterSignals(now=1.0))
    victim = str(next(iter(asc.im.instances(
        InstanceState.RUNNING))).scheduler_node_id)
    pm = lambda t: [{"id": "9-hang", "ts": float(t), "reason": "hang",
                     "node": victim}]
    ca.tick(signals=ClusterSignals(now=10.0, postmortems=pm(10)))
    ca.tick(signals=ClusterSignals(now=11.0, postmortems=pm(11)))
    old = GLOBAL_CONFIG.testing_rpc_failure
    GLOBAL_CONFIG.testing_rpc_failure = "cluster_autoscale=1.0"
    fault_injection.reset_injector()
    try:
        out = ca.tick(signals=ClusterSignals(now=12.0, postmortems=pm(12)))
        # Tipping postmortem arrived but actuation was injected to fail:
        # the node is NOT quarantined and the cluster is untouched.
        assert out["quarantined"] == []
        assert victim not in ca.quarantine.quarantined
        assert asc.config.node_types["train"].max_workers == 2
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = old
        fault_injection.reset_injector()
    out = ca.tick(signals=ClusterSignals(now=13.0, postmortems=pm(13)))
    assert out["quarantined"] == [victim]


def test_node_killed_mid_scale_up_converges_to_target():
    ca, asc, provider, _ = _mk(
        {"serve": NodeTypeConfig(resources={"CPU": 8.0}, max_workers=10)},
        _serve_policy(upscale_delay_s=0.0, upscale_cooldown_s=0.0))
    ca.tick(signals=ClusterSignals(now=0.0, serve_request_rate=600.0))
    assert len(provider.non_terminated_nodes()) == 6
    provider.kill(provider.non_terminated_nodes()[0])  # behind our back
    for t in range(1, 6):
        ca.tick(signals=ClusterSignals(now=float(t),
                                       serve_request_rate=600.0))
    # Drift reconcile failed the dead instance; the target relaunched it.
    assert len(provider.non_terminated_nodes()) == 6
    assert asc.im.active_counts()["serve"] == 6


# ------------------------------------------------------------- monitor
def test_monitor_stop_joins_thread_and_retires_watchdog_source(monkeypatch):
    monkeypatch.setenv("RAY_TPU_HANG_WATCHDOG", "0")
    from ray_tpu.util import watchdog

    watchdog.reset_watchdog()
    scheduler = ClusterScheduler()
    provider = SimProvider(scheduler)
    storage = tempfile.NamedTemporaryFile(suffix=".json", delete=False).name
    os.unlink(storage)
    asc = Autoscaler(
        AutoscalerConfig(
            node_types={"w": NodeTypeConfig(resources={"CPU": 1.0},
                                            min_workers=1, max_workers=2)},
            idle_timeout_s=1e9, cluster_name="test-monitor"),
        provider, scheduler=scheduler, storage_path=storage)
    monitor = Monitor(asc, interval_s=0.02).start()
    _wait(lambda: len(provider.non_terminated_nodes()) == 1,
          msg="monitor launched min_workers")
    _wait(lambda: "cluster.monitor" in watchdog.get_watchdog()._sources,
          msg="monitor heartbeat registered")
    monitor.stop()
    # stop() joined the tick thread: no reconcile pass survives the
    # return, so no launch can land afterwards.
    assert not monitor._thread.is_alive()
    assert list(asc.im.instances(InstanceState.REQUESTED)) == []
    n_before = provider.created
    time.sleep(0.1)
    assert provider.created == n_before
    # The beat source is retired (a stopped monitor is not a hang) and
    # the scheduler stops advertising autoscalable shapes.
    assert "cluster.monitor" not in watchdog.get_watchdog()._sources
    assert scheduler.autoscaling_enabled is False
    assert scheduler.autoscaler_node_shapes == []
    monitor.stop()  # idempotent: second stop is a no-op, not an error


# ------------------------------------------------------------ draining
def test_set_node_draining_excludes_node_from_placement():
    scheduler = ClusterScheduler()
    nid = scheduler.add_node({"CPU": 4.0})
    with scheduler._lock:
        assert scheduler._try_place_locked({"CPU": 1.0},
                                           DefaultStrategy()) == nid
        scheduler._nodes[nid].available = dict(
            scheduler._nodes[nid].total)  # undo the trial placement
    assert scheduler.set_node_draining(str(nid)) is True
    node = scheduler.get_node(nid)
    assert node.alive and not node.schedulable
    assert node.snapshot()["Draining"] is True
    with scheduler._lock:
        assert scheduler._try_place_locked({"CPU": 1.0},
                                           DefaultStrategy()) is None
    # Undrain restores eligibility; unknown nodes report False (the
    # drain raced a termination — fine, nothing to exclude).
    assert scheduler.set_node_draining(nid, False) is True
    assert scheduler.get_node(nid).schedulable
    assert scheduler.set_node_draining("no-such-node") is False


# ------------------------------------------------- provider idempotency
def test_fake_provider_terminate_is_idempotent(ray_init):
    provider = FakeNodeProvider()
    pid = provider.create_node("w", {"CPU": 1.0}, {})
    assert pid in provider.non_terminated_nodes()
    provider.terminate_node(pid)
    assert pid not in provider.non_terminated_nodes()
    provider.terminate_node(pid)  # double-terminate: no-op, no KeyError
    provider.terminate_node("fake-never-existed")


def test_subprocess_provider_terminate_is_idempotent():
    provider = SubprocessNodeProvider(address="tcp://127.0.0.1:0")
    provider.terminate_node("proc-99999")  # never seen: no-op
    provider.terminate_node("proc-99999")


def test_tpu_pod_provider_terminate_is_idempotent():
    provider = TPUPodProvider()
    provider.terminate_node("fake-never-existed")
    provider.terminate_node("fake-never-existed")


# ------------------------------------------------------------- signals
def test_collector_keeps_only_node_attributed_health_postmortems(
        monkeypatch):
    from ray_tpu.util import forensics

    rows = [
        {"id": "1-actor_death", "ts": 1.0, "reason": "actor_death",
         "node": "n1"},                                      # kept
        {"id": "2-actor_death", "ts": 2.0, "reason": "actor_death",
         "node": None},                                      # unattributed
        {"id": "3-manual", "ts": 3.0, "reason": "manual", "node": "n1"},
        {"id": "4-task_stall", "ts": 4.0, "reason": "task_stall:step",
         "node": "n2"},                                      # kept (prefix)
    ]
    monkeypatch.setattr(forensics, "list_postmortems", lambda: rows)
    got = SignalCollector()._postmortems()
    assert [r["id"] for r in got] == ["1-actor_death", "4-task_stall"]
    assert all(r["node"] for r in got)


def test_collector_snapshot_is_keyed_on_supplied_now(monkeypatch):
    from ray_tpu.util import forensics

    monkeypatch.setattr(forensics, "list_postmortems", lambda: [])
    scheduler = ClusterScheduler()
    sig = SignalCollector(scheduler=scheduler).collect(now=12345.0)
    assert sig.now == 12345.0
    assert sig.static_demand == []
    assert sig.postmortems == []


# ------------------------------------------------------ ledger locality
def test_ledger_claim_prefer_orders_without_skipping():
    ledger = SampleLedger(list(range(10)), seal_on_claim=True)
    even = lambda i: i % 2 == 0
    assert ledger.claim(4, prefer=even) == (0, 2, 4, 6)
    # Preferred indices exhaust mid-claim: the remainder fills from the
    # queue head in order — nothing is ever skipped.
    assert ledger.claim(4, prefer=even) == (8, 1, 3, 5)
    assert ledger.claim(4, prefer=even) == (7, 9)
    assert ledger.claim(1, prefer=even) is None
    # Exactly-once accounting is untouched by the ordering hint.
    assert ledger.double_trained() == []
    assert ledger.untrained() == []


def test_ledger_prefer_claims_roll_back_like_any_other():
    ledger = SampleLedger(list(range(6)))
    got = ledger.claim(3, step=5, prefer=lambda i: i >= 3)
    assert got == (3, 4, 5)
    assert ledger.rollback(None) == 3  # nothing committed: all requeued
    # Requeued at the front in original claim order, ahead of 0,1,2.
    assert ledger.claim(6, step=6) == (3, 4, 5, 0, 1, 2)


# ------------------------------------------------------ ingest locality
def test_plan_locality_and_block_source_degrade_without_runtime():
    from ray_tpu.data.ingest import executor as ingest_ex
    from ray_tpu.data.plan import InputData

    # Raw in-memory blocks carry no placement: locality-blind, by design.
    assert ingest_ex.plan_locality(InputData([[1, 2, 3]])) is None

    class _Ref:
        id = None

    assert ingest_ex.block_source(_Ref()) == "local"
    assert ingest_ex.block_source(object()) == "local"
