"""ray_tpu.checkpoint subsystem: layout round-trips, sharded two-phase
commit, torn-directory safety, coordinator restart scan, epoch fencing,
in-memory replica tier, elastic restore — plus the two regression fixes
that rode along (CheckpointManager rescan, save_pytree atomicity) and the
slow async-vs-sync blocking envelope (Check-N-Run, NSDI '22)."""

import collections
import json
import os
import shutil
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.checkpoint import (
    CheckpointCoordinator,
    ShardWriter,
    is_committed_dir,
    latest_committed_step,
    materialize_from_payloads,
    restore_latest,
    restore_pytree,
)
from ray_tpu.checkpoint import layout


def _orbax_available() -> bool:
    """save_pytree/load_pytree persist through orbax; environments without
    it still get the full sharded-2PC subsystem (numpy-backed)."""
    try:
        import orbax.checkpoint  # noqa: F401
    except Exception:
        return False
    return True


requires_orbax = pytest.mark.skipif(
    not _orbax_available(),
    reason="this environment has no orbax-checkpoint (pytree persistence "
           "backend for the legacy single-dir layout)")


def _tree(scale: float):
    """A pytree with a shardable matrix, a scalar, and nested containers."""
    return {
        "w": (np.arange(32, dtype=np.float32).reshape(8, 4) + 1) * scale,
        "b": np.float32(scale),
        "opt": [np.ones((3,), np.float32) * scale,
                {"m": np.full((2, 2), scale, np.float32)}],
    }


def _assert_trees_equal(got, want):
    jax.tree.map(
        lambda g, w: np.testing.assert_allclose(np.asarray(g), np.asarray(w)),
        got, want)


# ------------------------------------------------------------------ layout

def test_single_shard_save_commit_restore(tmp_path):
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    w = ShardWriter(coord, shard_id=0, world_size=1, replicate=False)
    handle = w.save_async(0, _tree(1.0))
    manifest = handle.result(timeout=30)
    assert manifest["shard_id"] == 0 and manifest["bytes"] > 0
    w.drain(timeout=30)
    w.close()
    assert coord.latest_committed() == 0
    assert is_committed_dir(layout.final_dir(root, 0))
    _assert_trees_equal(restore_latest(root), _tree(1.0))


def test_two_phase_commit_partial_shard_set_never_visible(tmp_path):
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    writers = [ShardWriter(coord, shard_id=i, world_size=2, replicate=False)
               for i in range(2)]
    tree = _tree(2.0)
    # Only shard 0 lands: the step must stay pending — invisible to every
    # reader — no matter how long it sits there.
    writers[0].save_async(0, tree).result(timeout=30)
    assert coord.latest_committed() is None
    assert latest_committed_step(root) is None
    assert os.path.isdir(layout.tmp_dir(root, 0))  # phase 1 in flight
    assert not os.path.exists(layout.final_dir(root, 0))
    # The second shard completes the set -> atomic commit.
    writers[1].save_async(0, tree).result(timeout=30)
    assert coord.latest_committed() == 0
    assert not os.path.exists(layout.tmp_dir(root, 0))
    restored = restore_pytree(layout.final_dir(root, 0))
    _assert_trees_equal(restored, tree)
    for w in writers:
        w.close()


def test_torn_directory_is_never_selected(tmp_path):
    """A checkpoint_N dir without the COMMIT marker (torn by a crashed
    external writer) must be invisible to selection and refuse restore."""
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(0, _tree(1.0)).result(timeout=30)
    w.close()
    # Hand-craft a torn NEWER step: final name, no COMMIT marker.
    torn = layout.final_dir(root, 7)
    shutil.copytree(layout.final_dir(root, 0), torn)
    os.remove(os.path.join(torn, layout.COMMIT_MARKER))
    assert not is_committed_dir(torn)
    assert latest_committed_step(root) == 0  # selection skips step 7
    with pytest.raises(ValueError, match="torn"):
        restore_pytree(torn)
    # A fresh coordinator's disk scan skips it too.
    assert CheckpointCoordinator(
        root, replicate_to_peer=False).latest_committed() == 0


def test_coordinator_restart_rescan_and_stale_tmp_sweep(tmp_path):
    root = str(tmp_path)
    c1 = CheckpointCoordinator(root, replicate_to_peer=False)
    w = ShardWriter(c1, 0, 1, replicate=False)
    for step in range(3):
        w.save_async(step, _tree(step + 1.0)).result(timeout=30)
    w.close()
    # A crashed save's leftover tmp dir...
    os.makedirs(layout.tmp_dir(root, 9))
    # ...a restarted coordinator rebuilds committed state and reclaims it.
    c2 = CheckpointCoordinator(root, replicate_to_peer=False)
    assert c2.committed_steps() == [0, 1, 2]
    assert not os.path.exists(layout.tmp_dir(root, 9))
    _assert_trees_equal(restore_latest(root), _tree(3.0))


def test_retention_keeps_last_k_committed(tmp_path):
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, keep=2, replicate_to_peer=False)
    w = ShardWriter(coord, 0, 1, replicate=False)
    for step in range(4):
        w.save_async(step, _tree(step + 1.0)).result(timeout=30)
    w.close()
    assert coord.committed_steps() == [2, 3]
    on_disk = sorted(d for d in os.listdir(root) if layout.parse_step(d))
    assert on_disk == [layout.step_dirname(2), layout.step_dirname(3)]


def test_epoch_fencing_discards_stale_attempt(tmp_path):
    """Shards from a crashed attempt must never mix into a newer attempt's
    save of the same step (would commit a torn mixed-attempt state)."""
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    e1 = coord.new_epoch()
    coord.begin_save(5, num_shards=2, epoch=e1)
    # The attempt dies; the trainer fences it off with a new epoch.
    e2 = coord.new_epoch()
    # A straggler shard from the dead attempt reports: discarded.
    assert coord.shard_complete(5, 0, {"bytes": 1}, epoch=e1) is False
    assert coord.latest_committed() is None
    # The new attempt reuses the step number cleanly (world size changed
    # too — the stale pending is dropped wholesale).
    w = ShardWriter(coord, 0, 1, epoch=e2, replicate=False)
    w.save_async(5, _tree(9.0)).result(timeout=30)
    w.close()
    assert coord.latest_committed() == 5
    # Even later stragglers of the committed step are inert.
    assert coord.shard_complete(5, 1, {"bytes": 1}, epoch=e1) is False
    _assert_trees_equal(restore_latest(root), _tree(9.0))


def test_aborted_step_cannot_be_resurrected_by_sibling(tmp_path):
    """After one shard aborts a step, a sibling shard arriving later must
    not re-open the pending entry (it would dangle forever, 1/2 done)."""
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    coord.begin_save(3, num_shards=2, epoch=0)
    coord.shard_failed(3, 0, "disk full", epoch=0)
    with pytest.raises(RuntimeError, match="aborted"):
        coord.begin_save(3, num_shards=2, epoch=0)
    assert coord.shard_complete(3, 1, {"bytes": 1}, epoch=0) is False
    assert coord.stats()["pending_steps"] == []
    # A later epoch may retry the same step number.
    e2 = coord.new_epoch()
    w = ShardWriter(coord, 0, 1, epoch=e2, replicate=False)
    w.save_async(3, _tree(4.0)).result(timeout=30)
    w.close()
    assert coord.latest_committed() == 3


def test_commit_survives_concurrent_begin_save_sweep(tmp_path, monkeypatch):
    """Regression: shard_complete used to drop the step from _pending
    before running phase 2, so a concurrent begin_save's stale-tmp sweep
    saw the committing step's .tmp dir as unowned and rmtree'd it
    mid-commit.  The step must stay registered until the rename."""
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    real_commit = layout.commit_step_dir

    def commit_with_interleaved_save(c_root, step, manifests, extra=None):
        # Another writer begins the NEXT step exactly while phase 2 runs —
        # its sweep must not reclaim the committing step's tmp dir.
        coord.begin_save(step + 1, num_shards=1, epoch=0)
        assert os.path.isdir(layout.tmp_dir(c_root, step)), \
            "stale-tmp sweep reclaimed a committing step's tmp dir"
        return real_commit(c_root, step, manifests, extra=extra)

    monkeypatch.setattr(layout, "commit_step_dir", commit_with_interleaved_save)
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(0, _tree(1.0)).result(timeout=30)
    w.close()
    monkeypatch.undo()
    assert coord.latest_committed() == 0
    final = layout.final_dir(root, 0)
    assert is_committed_dir(final)
    _assert_trees_equal(restore_pytree(final), _tree(1.0))


def test_none_leaf_roundtrips_and_object_leaf_rejected(tmp_path):
    """Regression: a None leaf became an object-dtype array that np.savez
    pickled — the save committed, but allow_pickle=False restore could
    never load it.  None now rides inline in the skeleton doc; any other
    non-numeric leaf fails the save loudly instead of committing an
    unrestorable checkpoint."""
    root = str(tmp_path / "none_ok")
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    tree = {"w": np.ones((4, 2), np.float32), "extra": None,
            "opt": [None, np.float32(2.0)]}
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(0, tree).result(timeout=30)
    w.close()
    restored = restore_latest(root)
    assert restored["extra"] is None and restored["opt"][0] is None
    np.testing.assert_allclose(restored["w"], 1.0)
    np.testing.assert_allclose(restored["opt"][1], 2.0)

    coord2 = CheckpointCoordinator(str(tmp_path / "obj"),
                                   replicate_to_peer=False)
    w2 = ShardWriter(coord2, 0, 1, replicate=False)
    h = w2.save_async(0, {"bad": object()})
    assert isinstance(h.exception(timeout=30), TypeError)
    w2.close()
    assert coord2.latest_committed() is None


def test_aborted_set_pruned_after_commit(tmp_path):
    """Regression: _aborted grew one poison entry per failed save forever.
    A commit prunes every entry at/below it — writers allocate step ids
    monotonically, so those steps can never be retried anyway."""
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    coord.begin_save(0, num_shards=2, epoch=0)
    coord.shard_failed(0, 0, "disk full", epoch=0)
    assert coord.stats()["aborted_entries"] == 1
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(1, _tree(1.0)).result(timeout=30)
    w.close()
    assert coord.latest_committed() == 1
    assert coord.stats()["aborted_entries"] == 0


TrainState = collections.namedtuple("TrainState", ["w", "count"])


def test_skeleton_pickle_fallback_for_custom_pytree_nodes(tmp_path):
    """Non-plain containers (namedtuples — e.g. optax states) round-trip
    through the pickled-treedef skeleton, preserving the node types
    (the pickle skeleton needs the class importable, hence module-level)."""
    tree = TrainState(w=np.arange(8, dtype=np.float32), count=np.int32(4))
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(0, tree).result(timeout=30)
    w.close()
    restored = restore_latest(root)
    assert type(restored).__name__ == "TrainState"
    np.testing.assert_allclose(restored.w, tree.w)
    assert int(restored.count) == 4


# ------------------------------------------------------------ replica tier

def test_replica_tier_memory_restore(ray_start_regular, tmp_path):
    """Writers register in-object-store shard snapshots; restore prefers
    the memory tier and rebuilds a committed dir without touching the
    original storage (Gemini fast recovery)."""
    root = str(tmp_path / "primary")
    coord = ray_tpu.remote(CheckpointCoordinator).remote(
        root, replica_steps=2, replicate_to_peer=False)
    writers = [ShardWriter(coord, shard_id=i, world_size=2) for i in range(2)]
    for step in range(2):
        handles = [w.save_async(step, _tree(step + 1.0)) for w in writers]
        for h in handles:
            h.result(timeout=60)
    for w in writers:
        w.drain(timeout=60)
        w.close()
    src = ray_tpu.get(coord.restore_source.remote())
    assert src["step"] == 1
    assert src["replicas"] is not None and src["replicas"]["step"] == 1
    payloads = {sid: ray_tpu.get(wrapped["ref"])
                for sid, wrapped in src["replicas"]["refs"].items()}
    assert sorted(payloads) == [0, 1]
    # Pure in-memory reassembly matches the disk copy...
    _assert_trees_equal(layout.assemble_from_payloads(payloads), _tree(2.0))
    # ...and materializing into a DIFFERENT root yields a committed dir.
    mem_root = str(tmp_path / "recovered")
    path = materialize_from_payloads(mem_root, 1, payloads)
    assert is_committed_dir(path)
    _assert_trees_equal(restore_pytree(path, _source="memory"), _tree(2.0))


def test_replica_tier_trims_to_last_k(ray_start_regular, tmp_path):
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replica_steps=1,
                                  replicate_to_peer=False)
    w = ShardWriter(coord, 0, 1)
    for step in range(3):
        w.save_async(step, _tree(step + 1.0)).result(timeout=30)
    w.close()
    stats = coord.stats()
    assert stats["committed_steps"] == [0, 1, 2]
    assert stats["replica_steps"] == [2]  # only the newest step resident


def test_peer_holder_placement_and_fetch(ray_start_cluster, tmp_path):
    """On a multi-node cluster the holder lands on a non-head node and
    keeps a materialized copy; on a single-node cluster it degrades to
    None (object-store tier only)."""
    from ray_tpu.checkpoint.replica import start_peer_holder

    cluster = ray_start_cluster
    assert start_peer_holder() is None  # single node: nowhere to put it
    cluster.add_node(num_cpus=2)
    holder = start_peer_holder()
    assert holder is not None
    payload = {"doc": {"leaves": []}, "skeleton": None, "kind": "json",
               "arrays": {"leaf_0": np.ones(4, np.float32)},
               "shard_id": 0, "step": 3}
    ref = ray_tpu.put(payload)
    ray_tpu.get(holder.hold.remote(3, 0, {"ref": ref}))
    assert ray_tpu.get(holder.held.remote()) == [(3, 0)]
    fetched = ray_tpu.get(holder.fetch.remote(3))
    np.testing.assert_allclose(fetched[0]["arrays"]["leaf_0"], 1.0)
    ray_tpu.get(holder.trim.remote([]))
    assert ray_tpu.get(holder.held.remote()) == []


# ---------------------------------------------------------- elastic restore

def test_elastic_restore_onto_larger_mesh(tmp_path):
    """Written by world_size=2, restored onto a 4-device mesh: the leaves
    reassemble on host and device_put with the new mesh's sharding."""
    from jax.sharding import Mesh

    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    writers = [ShardWriter(coord, shard_id=i, world_size=2, replicate=False)
               for i in range(2)]
    tree = {"w": np.arange(32, dtype=np.float32).reshape(8, 4),
            "b": np.float32(3.0)}
    for w in writers:
        w.save_async(0, tree).result(timeout=30)
        w.close()
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("x",))
    restored = restore_latest(root, mesh=mesh)
    np.testing.assert_allclose(np.asarray(restored["w"]), tree["w"])
    # axis 0 (8) divides the 4-device axis -> sharded across all 4 devices
    assert len(restored["w"].sharding.device_set) == 4
    # the scalar cannot shard -> replicated, still correct
    assert float(restored["b"]) == 3.0


def test_elastic_restore_world_size_down_to_one(tmp_path):
    """2-shard checkpoint restored with no mesh at all (host numpy) — the
    degenerate elastic case a single-process eval job hits."""
    root = str(tmp_path)
    coord = CheckpointCoordinator(root, replicate_to_peer=False)
    tree = _tree(5.0)
    for i in range(2):
        w = ShardWriter(coord, shard_id=i, world_size=2, replicate=False)
        w.save_async(0, tree).result(timeout=30)
        w.close()
    restored = restore_latest(root)
    _assert_trees_equal(restored, tree)
    assert isinstance(restored["w"], np.ndarray)


# ------------------------------------------- regression: manager + pytree IO

def test_checkpoint_manager_rescan_survives_restart(tmp_path):
    """Satellite regression: a fresh CheckpointManager on an existing
    storage_path must see the checkpoints already on disk instead of
    returning None / clobbering them from index 1."""
    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

    storage = str(tmp_path)
    m1 = CheckpointManager(storage, num_to_keep=5, score_attribute="score")
    for i in range(3):
        src = tempfile.mkdtemp()
        with open(os.path.join(src, "data.json"), "w") as f:
            json.dump({"step": i}, f)
        m1.register(Checkpoint(src), {"score": float(i)})
    # Driver restart: a brand-new manager over the same path.
    m2 = CheckpointManager(storage, num_to_keep=5, score_attribute="score")
    latest = m2.latest_checkpoint()
    assert latest is not None and latest.get_metadata()["index"] == 3
    best = m2.best_checkpoint()
    assert best.get_metadata()["metrics"]["score"] == 2.0
    # The counter continues where it left off — no index collision.
    src = tempfile.mkdtemp()
    with open(os.path.join(src, "data.json"), "w") as f:
        json.dump({"step": 3}, f)
    c4 = m2.register(Checkpoint(src), {"score": 3.0})
    assert c4.path.endswith("checkpoint_000004")


def test_checkpoint_manager_rescan_skips_torn_sharded_dirs(tmp_path):
    """A torn coordinator dir (shards present, no COMMIT) sitting in the
    manager's storage path must never be registered."""
    from ray_tpu.train.checkpoint import CheckpointManager

    storage = str(tmp_path)
    torn = os.path.join(storage, "checkpoint_000009")
    os.makedirs(os.path.join(torn, layout.shard_dirname(0)))
    m = CheckpointManager(storage)
    assert m.latest_checkpoint() is None
    # A committed coordinator dir IS picked up (no metadata.json needed).
    coord = CheckpointCoordinator(storage, replicate_to_peer=False)
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(2, _tree(1.0)).result(timeout=30)
    w.close()
    m2 = CheckpointManager(storage)
    latest = m2.latest_checkpoint()
    assert latest is not None and latest.path.endswith("checkpoint_000002")
    _assert_trees_equal(latest.to_pytree(), _tree(1.0))


def test_manager_register_never_clobbers_committed_sharded_dir(tmp_path):
    """Regression: manager.register rmtree'd a colliding coordinator-
    committed dir (the two sides number checkpoint_NNNNNN from independent
    counters).  It must skip past the committed step instead, and manager
    retention must leave COMMIT-marked dirs to the coordinator."""
    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

    storage = str(tmp_path)
    m = CheckpointManager(storage, num_to_keep=5)
    # The coordinator commits step 1 into the same path AFTER the
    # manager's rescan, so the manager's counter is still 0.
    coord = CheckpointCoordinator(storage, replicate_to_peer=False)
    w = ShardWriter(coord, 0, 1, replicate=False)
    w.save_async(1, _tree(7.0)).result(timeout=30)
    w.close()
    committed = layout.final_dir(storage, 1)
    assert is_committed_dir(committed)

    src = tempfile.mkdtemp()
    with open(os.path.join(src, "data.json"), "w") as f:
        json.dump({}, f)
    managed = m.register(Checkpoint(src), {"score": 1.0})
    # The committed dir survived; the legacy checkpoint landed past it.
    assert is_committed_dir(committed)
    _assert_trees_equal(restore_pytree(committed), _tree(7.0))
    assert managed.path.endswith("checkpoint_000002")


@requires_orbax
def test_save_pytree_crash_mid_save_preserves_previous(tmp_path, monkeypatch):
    """Satellite regression: save_pytree used to rmtree the old checkpoint
    BEFORE writing the new one — a crash mid-save destroyed both.  Now the
    write goes to a tmp sibling and the old dir survives any crash."""
    from ray_tpu.train import checkpoint as tckpt

    path = str(tmp_path / "pytree")
    tckpt.save_pytree({"w": np.ones(4, np.float32)}, path)

    def crashing(tree, p):
        os.makedirs(p, exist_ok=True)
        with open(os.path.join(p, "partial"), "w") as f:
            f.write("garbage")
        raise RuntimeError("simulated crash mid-save")

    monkeypatch.setattr(tckpt, "_orbax_save", crashing)
    with pytest.raises(RuntimeError, match="mid-save"):
        tckpt.save_pytree({"w": np.zeros(4, np.float32)}, path)
    monkeypatch.undo()
    # The previous checkpoint is intact...
    np.testing.assert_allclose(np.asarray(tckpt.load_pytree(path)["w"]), 1.0)
    # ...and the next save reclaims the stale tmp and lands normally.
    tckpt.save_pytree({"w": np.full(4, 2.0, np.float32)}, path)
    assert not os.path.exists(path + ".tmp")
    np.testing.assert_allclose(np.asarray(tckpt.load_pytree(path)["w"]), 2.0)


# --------------------------------------------------- trainer happy path

def test_trainer_async_save_commits_and_resumes(ray_start_regular, tmp_path):
    """async_save=True end-to-end: raw-pytree report() -> sharded commit
    per step, retention applied, result checkpoint restores."""
    from ray_tpu import train
    from ray_tpu.train import (CheckpointConfig, JaxTrainer, RunConfig,
                               ScalingConfig)

    storage = str(tmp_path)

    def loop(config):
        for it in range(4):
            train.report(
                {"step": it},
                checkpoint={"step": jnp.asarray(it),
                            "w": jnp.full((8, 2), float(it))})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="async_ckpt", storage_path=storage,
            checkpoint_config=CheckpointConfig(num_to_keep=2,
                                               async_save=True)))
    result = trainer.fit()
    assert result.error is None
    root = os.path.join(storage, "async_ckpt", "checkpoints", "sharded")
    assert latest_committed_step(root) == 3
    committed = layout.list_committed_steps(root)
    assert committed == [2, 3]  # retention kept the last 2
    assert result.checkpoint is not None
    restored = result.checkpoint.to_pytree()
    assert int(np.asarray(restored["step"])) == 3
    np.testing.assert_allclose(np.asarray(restored["w"]), 3.0)


# ----------------------------------------------------- slow: async envelope

@pytest.mark.slow
def test_async_save_blocks_under_quarter_of_sync(tmp_path):
    """Acceptance (ISSUE 5): with a multi-MB state, async save blocks the
    step for <= 25% of the sync save's wall time — only the device->host
    snapshot stays on the critical path."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_checkpoint", os.path.join(os.path.dirname(__file__), "..",
                                         "scripts", "bench_checkpoint.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    res = bench.measure_blocking(str(tmp_path), steps=4, payload_mb=64)
    assert res["async_vs_sync_block_ratio"] <= 0.25, res
