"""LLM inference engine (ISSUE 11): paged KV-cache, block-aware
scheduling, prefill/decode disaggregation with KV handoff, checkpoint-
backed model multiplexing, and the chaos/recovery paths.

Layering mirrors the subsystem: pure-logic unit tests on the block pool
and scheduler (deterministic FIFO/preemption traces), asyncio-driven
engine tests against the ``reference_generate`` oracle (any paging bug
changes tokens), then serve-level topology tests (monolithic vs
disaggregated byte-equality, multiplex LRU over committed checkpoints,
warm-replica routing, decode-replica kill recovery)."""

import argparse
import asyncio
import importlib.util
import os
import random
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.llm.blocks import BlockAllocator, BlockTable, NoFreeBlocks
from ray_tpu.serve.llm.engine import LLMEngine
from ray_tpu.serve.llm.model import ToyLM, lm_from_weights
from ray_tpu.serve.llm.scheduler import (EngineScheduler, FINISHED, RUNNING,
                                         Sequence, WAITING)


def _teardown_chaos():
    from ray_tpu._private.config import GLOBAL_CONFIG
    from ray_tpu._private.fault_injection import reset_injector

    GLOBAL_CONFIG.testing_rpc_failure = ""
    GLOBAL_CONFIG.testing_delay_us = 0
    reset_injector()


@pytest.fixture
def serve_llm(request):
    """Serve instance, optionally with a fault-injection spec param."""
    spec = getattr(request, "param", "")
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True,
                 _system_config={"testing_rpc_failure": spec})
    serve.start(http_options={"port": 0})
    yield
    serve.shutdown()
    ray_tpu.shutdown()
    _teardown_chaos()


# ===================================================== block pool (no ray)


class TestBlockAllocator:
    def test_fifo_alloc_free_order_deterministic(self):
        a = BlockAllocator(4, 2, pool="t-fifo")
        assert a.allocate(2) == [0, 1]
        assert a.allocate(1) == [2]
        a.free([0])
        a.free([2])
        # Freed ids re-enter FIFO: untouched tail first, then free order.
        assert a.allocate(3) == [3, 0, 2]
        assert a.num_free == 0
        assert a.num_in_use == 4

    def test_allocate_all_or_nothing(self):
        a = BlockAllocator(2, 2, pool="t-aon")
        with pytest.raises(NoFreeBlocks):
            a.allocate(3)
        assert a.num_free == 2
        assert a.num_in_use == 0

    def test_refcount_share_free_and_double_free(self):
        a = BlockAllocator(2, 2, pool="t-rc")
        (b,) = a.allocate(1)
        a.share([b])
        assert a.refcount(b) == 2
        a.free([b])
        assert a.refcount(b) == 1
        assert a.num_in_use == 1  # still held by one owner
        a.free([b])
        assert a.refcount(b) == 0
        assert a.num_free == 2
        with pytest.raises(ValueError):
            a.free([b])
        with pytest.raises(ValueError):
            a.share([b])

    def test_fork_shares_prefix_and_cow_diverges(self):
        a = BlockAllocator(8, 2, pool="t-cow")
        parent = BlockTable(a)
        for v in (10, 11, 12):
            parent.append(v)
        child = parent.fork()
        # Full prefix shared: same blocks, refcount 2, no new allocation.
        assert child.block_ids == parent.block_ids
        assert all(a.refcount(b) == 2 for b in parent.block_ids)
        assert a.num_in_use == 2
        # Parent writes into the shared (half-full) tail -> COW: parent
        # gets a private copy, child keeps the original, full blocks stay
        # shared untouched.
        parent.append(13)
        assert parent.block_ids[0] == child.block_ids[0]
        assert parent.block_ids[-1] != child.block_ids[-1]
        assert list(parent.entries()) == [10, 11, 12, 13]
        child.append(99)
        assert list(child.entries()) == [10, 11, 12, 99]
        assert list(parent.entries()) == [10, 11, 12, 13]  # not corrupted
        parent.release()
        child.release()
        assert a.num_in_use == 0
        assert a.num_free == 8

    def test_from_pages_is_all_or_nothing(self):
        a = BlockAllocator(2, 2, pool="t-fp")
        with pytest.raises(NoFreeBlocks):
            BlockTable.from_pages(a, [[1, 2], [3, 4], [5]])
        assert a.num_in_use == 0
        with pytest.raises(ValueError):
            BlockTable.from_pages(a, [[1, 2, 3]])  # page > block_size
        assert a.num_in_use == 0
        t = BlockTable.from_pages(a, [[1, 2], [3]])
        assert list(t.entries()) == [1, 2, 3]
        t.release()
        assert a.num_free == 2


# ====================================================== scheduler (no ray)


def _try_fill(sch, allocator, seq):
    """Simulate the prefill allocation for an admitted sequence (context
    plus the one token prefill generates), the way the engine does —
    rollback + preempt on NoFreeBlocks."""
    table = BlockTable(allocator)
    try:
        for i in range(len(seq.context()) + 1):
            table.append(i)
    except NoFreeBlocks:
        table.release()
        sch.preempt_seq(seq)
        return False
    seq.table = table
    return True


class TestEngineScheduler:
    def test_admit_headroom_and_head_of_line(self):
        a = BlockAllocator(8, 2, pool="t-admit")
        sch = EngineScheduler(a, watermark_blocks=2)
        s1 = Sequence([0] * 5, 4)   # needs ceil(6/2)=3 blocks
        s2 = Sequence([0] * 7, 4)   # needs ceil(8/2)=4 blocks
        s3 = Sequence([0], 4)       # needs 1 block, arrives last
        for s in (s1, s2, s3):
            sch.add(s)
        # Paced like the engine: one prefill per step, allocation between
        # admit calls (headroom is checked against the live pool).
        assert sch.admit(max_new=1) == [s1]   # 3 <= 8-2
        assert _try_fill(sch, a, s1)
        assert a.num_free == 5
        assert sch.admit(max_new=1) == []     # s2: 4 > 5-2
        # Head-of-line blocking: the short s3 stays queued behind s2.
        assert sch.waiting == [s2, s3]
        sch.finish(s1)
        assert a.num_free == 8
        assert sch.admit(max_new=1) == [s2]
        assert _try_fill(sch, a, s2)
        assert sch.admit(max_new=1) == [s3]   # 1 <= 4-2
        assert s2.status == RUNNING and s3.status == RUNNING

    def test_admit_priority_over_arrival(self):
        a = BlockAllocator(16, 2, pool="t-prio")
        sch = EngineScheduler(a)
        low = Sequence([0, 0], 4, priority=0)
        high = Sequence([0, 0], 4, priority=5)
        sch.add(low)
        sch.add(high)
        assert sch.admit() == [high, low]

    def test_admit_headroom_property(self):
        """Randomized invariant sweep: every admitted sequence had full
        headroom (context+1 plus watermark) at admit time; when waiting
        remains after an unbounded admit, the head did not fit; the pool
        never leaks across fill/finish/preempt churn."""
        rng = random.Random(1234)
        for trial in range(15):
            nb = rng.randrange(4, 32)
            bs = rng.randrange(1, 6)
            wm = rng.randrange(0, 3)
            a = BlockAllocator(nb, bs, pool=f"t-prop{trial}")
            sch = EngineScheduler(a, watermark_blocks=wm)
            for step in range(25):
                for _ in range(rng.randrange(0, 3)):
                    sch.add(Sequence([0] * rng.randrange(1, 3 * bs + 2),
                                     4, priority=rng.randrange(3)))
                free_before = a.num_free
                admitted = sch.admit()
                for seq in admitted:
                    need = a.blocks_needed(len(seq.context()) + 1)
                    assert free_before - wm >= need, (trial, step)
                if sch.waiting:
                    head = sch.waiting[0]
                    need = a.blocks_needed(len(head.context()) + 1)
                    assert a.num_free - wm < need, (trial, step)
                for seq in admitted:
                    _try_fill(sch, a, seq)
                for seq in list(sch.running):
                    if seq.table is not None and rng.random() < 0.4:
                        sch.finish(seq)
                assert a.num_free + a.num_in_use == nb, (trial, step)
            for seq in list(sch.running):
                sch.finish(seq)
            assert a.num_free == nb, trial

    def test_preemption_victim_is_lowest_priority_latest_arrival(self):
        a = BlockAllocator(3, 4, pool="t-victim")
        sch = EngineScheduler(a)
        s_hi = Sequence([0], 4, priority=1)
        s_lo_early = Sequence([0], 4, priority=0)
        s_lo_late = Sequence([0], 4, priority=0)
        for s in (s_hi, s_lo_early, s_lo_late):
            sch.add(s)
        assert len(sch.admit()) == 3
        for s in (s_hi, s_lo_early, s_lo_late):
            assert _try_fill(sch, a, s)
        assert a.num_free == 0

        victim = sch.preempt_one()
        assert victim is s_lo_late
        assert victim.status == WAITING
        assert victim.table is None
        assert victim.preemptions == 1
        assert sch.waiting[0] is victim  # front of the queue, not the back
        assert a.num_free == 1

        assert sch.preempt_one() is s_lo_early
        assert sch.preempt_one(protect=s_hi) is None  # nothing else to evict
        assert s_hi.status == RUNNING

    def test_ensure_decode_headroom_preempts_under_pressure(self):
        a = BlockAllocator(2, 2, pool="t-headroom")
        sch = EngineScheduler(a)
        s_hi = Sequence([0], 4, priority=1)
        s_lo = Sequence([0], 4, priority=0)
        sch.add(s_hi)
        sch.add(s_lo)
        assert len(sch.admit()) == 2
        for s in (s_hi, s_lo):
            assert _try_fill(sch, a, s)
        # Both tables sit on a full block (2 entries): the next decode
        # append needs 2 fresh blocks against 0 free.
        assert a.num_free == 0
        steppable = sch.ensure_decode_headroom()
        assert steppable == [s_hi]
        assert s_lo.status == WAITING
        assert a.num_free == 1


# =================================================== engine (asyncio, no ray)


class _FakeSlot:
    """Just enough of continuous.SequenceSlot for LLMEngine.step: the
    request, the per-stream state dict, and the cancellation flag."""

    def __init__(self, request):
        self.request = request
        self.state = {}
        self._cancelled = False


def _run_engine(engine, slots, max_steps=600):
    """Drive engine.step the way the continuous loop does (drop a slot on
    EOS or a terminal error); returns per-slot emission lists."""
    from ray_tpu.serve.continuous import EOS, Emissions

    out = {id(s): [] for s in slots}

    async def drive():
        live = list(slots)
        for _ in range(max_steps):
            if not live:
                return
            emissions = await engine.step(live)
            nxt = []
            for slot, em in zip(live, emissions):
                if em is EOS:
                    continue
                if isinstance(em, Emissions):
                    out[id(slot)].extend(em.items)
                    if em.eos:
                        continue
                elif isinstance(em, Exception):
                    out[id(slot)].append(em)
                    continue
                elif em is not None:
                    out[id(slot)].append(em)
                nxt.append(slot)
            live = nxt
        raise AssertionError("engine never retired all slots")

    asyncio.run(drive())
    return [out[id(s)] for s in slots]


class TestLLMEngine:
    def test_stream_matches_reference_oracle(self):
        model = ToyLM(seed=3)
        engine = LLMEngine(lambda k: model, num_blocks=64, block_size=4,
                           pool="t-eng1")
        slot = _FakeSlot({"prompt": [5, 6, 7], "max_tokens": 10})
        (toks,) = _run_engine(engine, [slot])
        assert toks == model.reference_generate([5, 6, 7], 10)
        assert engine.allocator.num_in_use == 0  # blocks freed at retire

    def test_adapter_groups_generate_their_own_streams(self):
        models = {
            "base": ToyLM(seed=3),
            "base::poet": ToyLM(seed=3, adapter_delta=[7] * 8),
        }
        engine = LLMEngine(lambda k: models[k], num_blocks=64, block_size=4,
                           pool="t-eng2")
        base_slot = _FakeSlot({"prompt": [1, 2], "max_tokens": 8})
        poet_slot = _FakeSlot({"prompt": [1, 2], "max_tokens": 8,
                               "adapter": "poet"})
        base_toks, poet_toks = _run_engine(engine, [base_slot, poet_slot])
        assert base_toks == models["base"].reference_generate([1, 2], 8)
        assert poet_toks == models["base::poet"].reference_generate([1, 2], 8)
        assert base_toks != poet_toks  # the adapter delta actually applied

    def test_tiny_pool_preempts_and_streams_stay_correct(self):
        """Pool far too small for all streams at once: admission gates,
        decode growth forces preemption, recompute-on-resume regenerates
        identical suffixes — every stream still matches the oracle."""
        model = ToyLM(seed=9)
        engine = LLMEngine(lambda k: model, num_blocks=8, block_size=2,
                           pool="t-eng3")
        prompts = [[i, i + 1, i + 2, i + 3, i + 4, i + 5] for i in range(3)]
        slots = [_FakeSlot({"prompt": p, "max_tokens": 8}) for p in prompts]
        outs = _run_engine(engine, slots)
        for p, toks in zip(prompts, outs):
            assert toks == model.reference_generate(p, 8)
        total_preemptions = sum(
            s.state["llm"].preemptions for s in slots)
        assert total_preemptions >= 1, "pool pressure never forced preemption"
        assert engine.allocator.num_in_use == 0

    def test_cancellation_reaps_blocks(self):
        model = ToyLM(seed=4)
        engine = LLMEngine(lambda k: model, num_blocks=64, block_size=4,
                           pool="t-eng4")
        slot = _FakeSlot({"prompt": [1, 2, 3], "max_tokens": 100})

        async def drive():
            for _ in range(5):
                await engine.step([slot])
            assert engine.allocator.num_in_use > 0
            # Client disconnect: the continuous loop flags the slot and
            # stops passing it; the engine must reap it next iteration.
            slot._cancelled = True
            await engine.step([])

        asyncio.run(drive())
        assert engine.allocator.num_in_use == 0
        assert not engine.scheduler.running
        assert not engine._tracked

    def test_decode_only_engine_rejects_missing_handoff(self):
        model = ToyLM(seed=4)
        engine = LLMEngine(lambda k: model, num_blocks=16, block_size=4,
                           pool="t-eng5", decode_only=True)
        slot = _FakeSlot({"prompt": [1], "max_tokens": 4})
        (out,) = _run_engine(engine, [slot])
        assert len(out) == 1 and isinstance(out[0], TypeError)


# ===================================== speculative decoding (asyncio, no ray)


def _spec_engine(model, *, spec_k, agreement, pool, num_blocks=64,
                 block_size=4):
    from ray_tpu.serve.llm.model import DraftLM

    draft = DraftLM(model, agreement=agreement)
    return LLMEngine(lambda k: model, num_blocks=num_blocks,
                     block_size=block_size, pool=pool, spec_k=spec_k,
                     get_draft_model=lambda k: draft)


class TestSpeculativeDecoding:
    """Every edge of the propose/verify/rollback seam against the
    ``reference_generate`` oracle: any divergence means a draft-KV page
    leaked into (or a real token fell out of) the sequence state."""

    def test_k1_matches_oracle(self):
        from ray_tpu.serve.llm import metrics as lm

        model = ToyLM(seed=21)
        engine = _spec_engine(model, spec_k=1, agreement=0.7,
                              pool="t-spec-k1")
        slot = _FakeSlot({"prompt": [3, 1, 4, 1, 5], "max_tokens": 14})
        (toks,) = _run_engine(engine, [slot])
        assert toks == model.reference_generate([3, 1, 4, 1, 5], 14)
        assert engine.allocator.num_in_use == 0
        assert lm.SPEC_PROPOSED_TOKENS.get(tags={"pool": "t-spec-k1"}) > 0

    def test_adversarial_draft_all_rejected_still_oracle(self):
        """agreement=0.0: every proposal dies at position 0, so every
        verify pass banks only the bonus token — same cadence as plain
        decoding, output still byte-identical, every draft page rolled
        back (accepted counter stays zero)."""
        from ray_tpu.serve.llm import metrics as lm

        pool = "t-spec-adv"
        model = ToyLM(seed=22)
        engine = _spec_engine(model, spec_k=4, agreement=0.0, pool=pool)
        slot = _FakeSlot({"prompt": [9, 8, 7], "max_tokens": 10})
        (toks,) = _run_engine(engine, [slot])
        assert toks == model.reference_generate([9, 8, 7], 10)
        assert engine.allocator.num_in_use == 0
        assert lm.SPEC_PROPOSED_TOKENS.get(tags={"pool": pool}) > 0
        assert lm.SPEC_ACCEPTED_TOKENS.get(tags={"pool": pool}) == 0
        assert lm.SPEC_ROLLBACK_TOKENS.get(tags={"pool": pool}) > 0

    def test_eos_inside_accepted_draft_run(self):
        """A stop token landing MID-run must cut the acceptance there:
        tokens past the stop would diverge from what a plain engine
        (which halts the moment it emits the stop) produces."""
        model = ToyLM(seed=23)
        prompt = [2, 7, 1, 8]
        ref = model.reference_generate(prompt, 16)
        # Stop on a token the stream hits mid-generation; with a perfect
        # draft (agreement=1.0) it lands inside a fully-accepted k-run.
        stop = ref[5]
        engine = _spec_engine(model, spec_k=4, agreement=1.0,
                              pool="t-spec-eos")
        slot = _FakeSlot({"prompt": prompt, "max_tokens": 16,
                          "stop_token": stop})
        (toks,) = _run_engine(engine, [slot])
        assert toks == ref[:6]  # ends exactly AT the stop, nothing after
        assert toks[-1] == stop
        assert engine.allocator.num_in_use == 0

    def test_draft_longer_than_remaining_budget(self):
        """spec_k far past max_tokens: the proposal clamps to the
        remaining budget BEFORE any page is appended (never draft what
        can't be banked), so the stream emits exactly max_tokens tokens
        with no extras from an over-long accepted run."""
        from ray_tpu.serve.llm import metrics as lm

        pool = "t-spec-clamp"
        model = ToyLM(seed=24)
        engine = _spec_engine(model, spec_k=8, agreement=1.0, pool=pool)
        slot = _FakeSlot({"prompt": [6, 6, 6], "max_tokens": 3})
        (toks,) = _run_engine(engine, [slot])
        assert toks == model.reference_generate([6, 6, 6], 3)
        assert len(toks) == 3
        assert engine.allocator.num_in_use == 0
        # Prefill banks token 1; ONE verify pass proposes exactly the
        # room left (2, not spec_k=8) and a perfect draft banks it all.
        assert lm.SPEC_PROPOSED_TOKENS.get(tags={"pool": pool}) == 2
        assert lm.SPEC_ACCEPTED_TOKENS.get(tags={"pool": pool}) == 2

    def test_preempt_mid_draft_rolls_back_refcount_exact(self):
        """NoFreeBlocks in the middle of appending provisional draft pages
        (a peer grabbed the pool between the headroom check and the
        append): every provisional page must come back before the
        scheduler releases the table — refcounts exact, and the preempted
        stream recomputes to the oracle."""
        from ray_tpu.serve.llm import metrics as lm
        from ray_tpu.serve.llm.model import DraftLM

        pool = "t-spec-pre"
        model = ToyLM(seed=25)
        draft = DraftLM(model, agreement=1.0)
        engine = LLMEngine(lambda k: model, num_blocks=8, block_size=4,
                           pool=pool, spec_k=4,
                           get_draft_model=lambda k: draft)
        # Prompt(5) + first token = 6 entries -> 2 blocks with 2 slack
        # slots: a 4-token draft run fits 2 appends then needs a block.
        slot = _FakeSlot({"prompt": [1, 2, 3, 4, 5], "max_tokens": 12})

        async def prefill_only():
            await engine.step([slot])

        asyncio.run(prefill_only())
        seq = slot.state["llm"]
        base = seq.table.num_tokens
        held = engine.allocator.num_in_use
        # A rival table hogs every free block: the draft's third append
        # has nowhere to go.
        hog = BlockTable(engine.allocator)
        while engine.allocator.num_free:
            hog.append(model.kv_entry(0, hog.num_tokens))
        rb_before = lm.SPEC_ROLLBACK_TOKENS.get(tags={"pool": pool})
        engine._spec_decode_one(model, draft, seq)
        # Preempted: provisional pages truncated BEFORE release, the
        # sequence's own pages freed, the hog's untouched.
        assert seq.status == WAITING and seq.preemptions == 1
        assert seq.table is None
        assert engine.allocator.num_in_use == \
            held - (base + 3) // 4 + len(hog.block_ids)
        assert lm.SPEC_ROLLBACK_TOKENS.get(tags={"pool": pool}) \
            - rb_before == 2
        # Pool pressure gone: recompute-on-resume must still hit the
        # oracle byte-for-byte (generated-so-far folds into the context).
        hog.release()
        (toks,) = _run_engine(engine, [slot])
        assert seq.generated == model.reference_generate(
            [1, 2, 3, 4, 5], 12)
        assert engine.allocator.num_in_use == 0

    def test_verify_chaos_degrades_to_plain_decode(self):
        """llm_spec_verify chaos (budget 2): each injected verify failure
        rolls every draft page back and banks ONE plain-decoded token —
        the streams end byte-identical (no torn or duplicated tokens) and
        the fallback counter ticks once per failure."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.fault_injection import reset_injector
        from ray_tpu.serve.llm import metrics as lm

        pool = "t-spec-chaos"
        GLOBAL_CONFIG.testing_rpc_failure = "llm_spec_verify=1.0:2"
        reset_injector()
        try:
            model = ToyLM(seed=26)
            engine = _spec_engine(model, spec_k=4, agreement=0.9, pool=pool)
            s1 = _FakeSlot({"prompt": [1, 2], "max_tokens": 12})
            s2 = _FakeSlot({"prompt": [3, 4], "max_tokens": 12})
            out1, out2 = _run_engine(engine, [s1, s2])
            assert out1 == model.reference_generate([1, 2], 12)
            assert out2 == model.reference_generate([3, 4], 12)
            assert lm.SPEC_FALLBACKS.get(tags={"pool": pool}) == 2
            assert engine.allocator.num_in_use == 0
        finally:
            GLOBAL_CONFIG.testing_rpc_failure = ""
            reset_injector()


# ============================================= KV handoff (asyncio, no ray)


class TestKVHandoff:
    def test_export_import_resume_matches_monolithic(self):
        """The disaggregation seam itself: prefill on one pool, export the
        KV pages, import into a decode-only engine — the combined stream is
        byte-identical to the monolithic oracle."""
        from ray_tpu.serve.llm import handoff as kvh

        model = ToyLM(seed=11)
        prompt = list(range(20))
        max_tokens = 12
        # Prefill side (its own pool, released after export).
        pa = BlockAllocator(32, 4, pool="t-hand-p")
        table = BlockTable(pa)
        first = model.prefill(table, prompt)
        payload = kvh.export_kv(table, prompt=prompt, generated=[first],
                                model="base", max_tokens=max_tokens)
        table.release()
        assert pa.num_in_use == 0
        assert payload["nbytes"] > 0

        # Decode side: the imported pages replace the prefill recompute;
        # the already-emitted first token is not re-emitted.
        engine = LLMEngine(lambda k: model, num_blocks=32, block_size=4,
                           pool="t-hand-d", decode_only=True)
        slot = _FakeSlot({"prompt": prompt, "max_tokens": max_tokens,
                          "handoff": payload})
        (toks,) = _run_engine(engine, [slot])
        assert [first] + toks == model.reference_generate(prompt, max_tokens)
        assert engine.allocator.num_in_use == 0

    def test_block_alloc_fault_isolated_to_one_stream(self):
        """llm_block_alloc chaos (budget 1): exactly one stream surfaces
        the injected failure, the other generates clean, and the pool
        accounting survives (no leaked partial prefill)."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.fault_injection import (InjectedFailure,
                                                      reset_injector)

        GLOBAL_CONFIG.testing_rpc_failure = "llm_block_alloc=1.0:1"
        reset_injector()
        try:
            model = ToyLM(seed=5)
            engine = LLMEngine(lambda k: model, num_blocks=64, block_size=4,
                               pool="t-fault")
            s1 = _FakeSlot({"prompt": [1, 2], "max_tokens": 6})
            s2 = _FakeSlot({"prompt": [3, 4], "max_tokens": 6})
            out1, out2 = _run_engine(engine, [s1, s2])
            # s1 is admitted first (max_prefill_per_step=1) and eats the
            # one-shot fault at its first block allocation.
            assert len(out1) == 1 and isinstance(out1[0], InjectedFailure)
            assert out2 == model.reference_generate([3, 4], 6)
            assert engine.allocator.num_in_use == 0
        finally:
            GLOBAL_CONFIG.testing_rpc_failure = ""
            reset_injector()


# ================================================= multiplex (asyncio, no ray)


class TestMultiplexUnload:
    def test_eviction_awaits_async_unload_and_updates_ids(self):
        from ray_tpu.serve.multiplex import multiplexed

        events = []

        class Model:
            def __init__(self, mid):
                self.mid = mid

            async def unload(self):
                events.append(("unload", self.mid))

        class Host:
            @multiplexed(max_num_models_per_replica=2)
            async def load(self, mid):
                events.append(("load", mid))
                return Model(mid)

        host = Host()

        async def drive():
            m1 = await host.load("m1")
            await host.load("m2")
            assert await host.load("m1") is m1  # hit refreshes LRU position
            await host.load("m3")               # evicts m2, not m1

        asyncio.run(drive())
        wrapper = Host.load._multiplex_wrappers[id(host)]
        assert wrapper.loaded_model_ids == ["m1", "m3"]
        assert ("unload", "m2") in events
        assert events.count(("load", "m1")) == 1

    def test_user_unload_callback_and_close_fallback(self):
        from ray_tpu.serve.multiplex import multiplexed

        unloaded = []

        @multiplexed(max_num_models_per_replica=1,
                     unload=lambda mid, model: unloaded.append(mid))
        async def load(mid):
            return mid

        # Without a callback the model's own close() runs on eviction —
        # the hook the ToyLM weights release through.
        models = {}

        @multiplexed(max_num_models_per_replica=1)
        async def load_lm(mid):
            models[mid] = ToyLM(seed=1)
            return models[mid]

        async def drive():
            await load("a")
            await load("b")
            await load_lm("x")
            await load_lm("y")

        asyncio.run(drive())
        assert unloaded == ["a"]
        assert models["x"].closed is True
        assert models["y"].closed is False


# ===================================================== router warm routing


class TestWarmReplicaRouting:
    def test_cold_replica_picked_when_warm_saturated(self):
        """Regression (ISSUE 11 satellite): a saturated warm replica must
        not absorb queued multiplexed requests — the pick degrades to the
        normal queue-aware choice and a cold replica loads the model."""
        from ray_tpu.serve.router import PowerOfTwoChoicesReplicaScheduler

        sch = PowerOfTwoChoicesReplicaScheduler()
        warm = {"replica_id": "r-warm", "actor": None,
                "max_ongoing_requests": 2, "multiplexed_model_ids": ["m1"]}
        cold = {"replica_id": "r-cold", "actor": None,
                "max_ongoing_requests": 2, "multiplexed_model_ids": []}
        sch.update_replicas([warm, cold])
        for _ in range(20):
            assert sch.choose_replica("m1")["replica_id"] == "r-warm"
        sch.on_request_sent("r-warm")
        sch.on_request_sent("r-warm")  # warm now at max_ongoing_requests
        for _ in range(20):
            assert sch.choose_replica("m1")["replica_id"] == "r-cold"
        sch.on_request_done("r-warm")  # a slot frees: warm preferred again
        for _ in range(20):
            assert sch.choose_replica("m1")["replica_id"] == "r-warm"

    def test_warm_routing_sticks_to_loaded_replica(self, serve_llm):
        @serve.deployment(num_replicas=2, max_ongoing_requests=4)
        class Host:
            @serve.multiplexed(max_num_models_per_replica=2)
            async def _load(self, mid):
                return mid

            async def __call__(self):
                from ray_tpu.serve import context as sc

                await self._load(sc.get_multiplexed_model_id())
                return sc.get_internal_replica_context().replica_id

        handle = serve.run(Host.bind(), name="warmroute", route_prefix=None)
        h = handle.options(multiplexed_model_id="m1")
        first = h.remote().result(timeout_s=30)
        # Wait for the loaded-ids metadata to round-trip replica ->
        # controller -> this router's long-poll.
        sch = handle._get_router()._scheduler
        deadline = time.time() + 15
        while time.time() < deadline:
            if any("m1" in (r.get("multiplexed_model_ids") or ())
                   for r in sch._replicas):
                break
            time.sleep(0.05)
        else:
            pytest.fail("multiplexed ids never reached the router")
        rids = {h.remote().result(timeout_s=30) for _ in range(12)}
        assert rids == {first}, "requests strayed off the warm replica"


# ==================================================== serve-level topologies


def _stream(handle, req):
    return list(handle.options(stream=True).remote(dict(req)))


class TestServeLLM:
    def test_monolithic_stream_matches_reference(self, serve_llm):
        from ray_tpu.serve.llm.disagg import build_monolithic_app

        specs = {"base": {"seed": 21, "dim": 8}}
        handle = serve.run(build_monolithic_app(model_specs=specs),
                           name="llmmono", route_prefix=None)
        prompt = [3, 1, 4, 1, 5]
        toks = _stream(handle, {"prompt": prompt, "max_tokens": 9})
        assert toks == ToyLM(seed=21).reference_generate(prompt, 9)

    def test_disagg_byte_identical_to_monolithic(self, serve_llm):
        from ray_tpu.serve.llm.disagg import (build_disagg_app,
                                              build_monolithic_app)

        specs = {
            "base": {"seed": 21, "dim": 8},
            "base::poet": {"seed": 21, "dim": 8, "adapter_delta": [3] * 8},
        }
        mono = serve.run(build_monolithic_app(model_specs=specs),
                         name="eqmono", route_prefix=None)
        dis = serve.run(build_disagg_app(model_specs=specs,
                                         prefill_replicas=1,
                                         decode_replicas=1),
                        name="eqdis", route_prefix=None)
        requests = [
            {"prompt": list(range(1, 9)), "max_tokens": 8},
            {"prompt": [42] * 20, "max_tokens": 12},
            {"prompt": [7, 8, 9], "max_tokens": 6, "adapter": "poet"},
            {"prompt": [1], "max_tokens": 1},
        ]
        for req in requests:
            a = _stream(mono, req)
            b = _stream(dis, req)
            assert a == b, f"topologies diverged on {req}"
            assert len(a) == req["max_tokens"]
        # And both match the oracle, adapter delta included.
        poet = lm_from_weights(specs["base::poet"])
        assert _stream(dis, requests[2]) \
            == poet.reference_generate([7, 8, 9], 6)

    def test_multiplex_lru_swap_over_committed_checkpoints(self, serve_llm,
                                                           tmp_path):
        """Five checkpoint-backed model keys through a 4-slot LRU: every
        response is correct for ITS weights across the swaps, and the
        least-recently-used key is the one evicted."""
        from ray_tpu.serve.llm.disagg import (_ModelHostMixin,
                                              build_monolithic_app)
        from ray_tpu.serve.llm.store import publish_model_weights

        root = str(tmp_path / "models")
        keys = []
        for i in range(5):
            key = "ck-base" if i == 0 else f"ck-base::a{i}"
            weights = {"seed": 17, "dim": 8}
            if i:
                weights["adapter_delta"] = [i] * 8
            publish_model_weights(root, key, weights)
            keys.append((key, weights))

        handle = serve.run(build_monolithic_app(ckpt_root=root),
                           name="mxswap", route_prefix=None)
        prompt = [2, 7, 1, 8]
        for key, weights in keys:
            req = {"prompt": prompt, "max_tokens": 5, "model": "ck-base"}
            if "::" in key:
                req["adapter"] = key.split("::", 1)[1]
            assert _stream(handle, req) \
                == lm_from_weights(weights).reference_generate(prompt, 5)
        # Revisit the second-loaded key: it must have survived (only the
        # head of the LRU fell out when the fifth key loaded) and still
        # serve the right weights after the churn.
        key1, weights1 = keys[1]
        req = {"prompt": prompt, "max_tokens": 5, "model": "ck-base",
               "adapter": key1.split("::", 1)[1]}
        assert _stream(handle, req) \
            == lm_from_weights(weights1).reference_generate(prompt, 5)

        # In-process introspection: find this replica's multiplex wrapper
        # and check the LRU evicted exactly the first-loaded key.
        ours = [w for w in
                _ModelHostMixin._load_model._multiplex_wrappers.values()
                if "ck-base::a1" in w.loaded_model_ids]
        assert ours, "multiplex wrapper not found"
        loaded = ours[-1].loaded_model_ids
        assert len(loaded) == 4
        assert "ck-base" not in loaded, "LRU head was not evicted"

    def test_unknown_checkpoint_key_errors_request_not_replica(self,
                                                               serve_llm,
                                                               tmp_path):
        from ray_tpu.serve.llm.disagg import build_monolithic_app
        from ray_tpu.serve.llm.store import publish_model_weights

        root = str(tmp_path / "models")
        publish_model_weights(root, "only", {"seed": 1, "dim": 8})
        handle = serve.run(build_monolithic_app(ckpt_root=root),
                           name="mxmiss", route_prefix=None)
        with pytest.raises(Exception):
            _stream(handle, {"prompt": [1], "max_tokens": 2,
                             "model": "never-published"})
        # The replica survived the bad key: a good request still works.
        ref = lm_from_weights({"seed": 1, "dim": 8})
        assert _stream(handle, {"prompt": [1, 2], "max_tokens": 3,
                                "model": "only"}) \
            == ref.reference_generate([1, 2], 3)


# ============================================================ chaos paths


@pytest.mark.parametrize("serve_llm", ["llm_kv_handoff=1.0:2"],
                         indirect=True)
def test_kv_handoff_fault_recovers_byte_identical(serve_llm):
    """llm_kv_handoff chaos: the first two KV-page imports fail on the
    decode side; the frontend re-prefills and the client stream is still
    byte-identical — no tear, no duplicate, no visible error."""
    from ray_tpu.serve.llm.disagg import build_disagg_app

    specs = {"base": {"seed": 31, "dim": 8}}
    handle = serve.run(build_disagg_app(model_specs=specs,
                                        decode_replicas=2),
                       name="kvchaos", route_prefix=None)
    prompt = list(range(10))
    toks = _stream(handle, {"prompt": prompt, "max_tokens": 12})
    assert toks == ToyLM(seed=31).reference_generate(prompt, 12)


def test_decode_replica_kill_mid_stream_no_torn_output(serve_llm):
    """Kill a decode replica while six streams are mid-generation: every
    stream re-prefills on the survivor and completes byte-identical to the
    oracle — exactly max_tokens tokens, no tears, no duplicates."""
    from ray_tpu._private.runtime import get_runtime
    from ray_tpu.serve.llm.disagg import build_disagg_app

    specs = {"base": {"seed": 41, "dim": 8}}
    handle = serve.run(build_disagg_app(model_specs=specs,
                                        decode_replicas=2,
                                        decode_step_time_s=0.01),
                       name="llmkill", route_prefix=None)
    n, max_tokens = 6, 24
    prompts = [[i, i + 1, i + 2, i + 3] for i in range(n)]
    refs = [ToyLM(seed=41).reference_generate(p, max_tokens)
            for p in prompts]

    partials = [[] for _ in range(n)]
    errors = []

    def client(i):
        try:
            for tok in handle.options(stream=True).remote(
                    {"prompt": prompts[i], "max_tokens": max_tokens}):
                partials[i].append(tok)
        except Exception as e:  # noqa: BLE001 — assert below
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    # Wait until streams are demonstrably flowing, then kill one decode
    # replica out from under them.
    deadline = time.time() + 30
    while time.time() < deadline:
        if sum(len(p) for p in partials) >= n:
            break
        time.sleep(0.01)
    else:
        pytest.fail(f"streams never started: {[len(p) for p in partials]}")

    dh = serve.get_deployment_handle("DecodeWorker", "llmkill")
    sch = dh._get_router()._scheduler
    deadline = time.time() + 10
    while time.time() < deadline and sch.num_replicas < 2:
        time.sleep(0.05)
    assert sch.num_replicas == 2
    from chaos_utils import kill_llm_decode_replica

    kill_llm_decode_replica("llmkill")

    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "stream hung after kill"
    assert not errors, errors
    for i in range(n):
        assert partials[i] == refs[i], f"stream {i} torn or duplicated"


# ================================== inference observability plane (ISSUE 12)


def test_metrics_accessors_live_during_disagg_run(serve_llm):
    """End-to-end accessor check: drive a disaggregated app and read every
    serve.metrics accessor while streams are in flight — KV utilization and
    batch occupancy come from live gauge samples (they read 0 once the pool
    drains), TTFT / inter-token / goodput from the finalized points."""
    from ray_tpu.serve import metrics as sm
    from ray_tpu.serve.llm.disagg import build_disagg_app

    specs = {"base": {"seed": 61, "dim": 8}}
    handle = serve.run(build_disagg_app(model_specs=specs,
                                        decode_replicas=1,
                                        decode_step_time_s=0.01,
                                        deployment_prefix="accessors_"),
                       name="accessors", route_prefix=None)
    n, max_tokens = 4, 20
    prompts = [[i, i + 1, i + 2] for i in range(n)]
    refs = [ToyLM(seed=61).reference_generate(p, max_tokens)
            for p in prompts]
    results = [None] * n

    def client(i):
        results[i] = _stream(handle, {"prompt": prompts[i],
                                      "max_tokens": max_tokens})

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    # Gauges fold into the time-series only when sampled, and counter
    # rates need samples on BOTH sides of the increments: poll for the
    # whole run, not just until the gauges go nonzero.
    kv_util = occupancy = 0.0
    deadline = time.time() + 20
    while time.time() < deadline and any(t.is_alive() for t in threads):
        kv_util = max(kv_util, sm.kv_utilization(pool="decode",
                                                 window_s=60.0))
        occupancy = max(occupancy, sm.batch_occupancy(pool="decode",
                                                      window_s=60.0))
        time.sleep(0.01)
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    assert results == refs  # observability never perturbed the streams
    assert 0.0 < kv_util <= 1.0
    assert 0.0 < occupancy <= 1.0
    assert sm.ttft_p99(deployment="accessors_LLMFrontend",
                       window_s=600.0) > 0.0
    assert sm.inter_token_p99(deployment="accessors_LLMFrontend",
                              window_s=600.0) > 0.0
    assert sm.goodput_tokens_per_s(window_s=600.0) > 0.0


@pytest.mark.parametrize("serve_llm", ["llm_kv_handoff=1.0:2"],
                         indirect=True)
def test_slo_burn_alert_fires_and_clears_under_kv_chaos(serve_llm):
    """SLO chaos: two injected KV-handoff failures force re-prefills whose
    oversized inter-token gaps burn the error budget — the watchdog alerts
    within one fast-window evaluation (visible in serve.status() and
    /api/serve/slo), clears after healthy traffic dilutes the fast window,
    and exports the episode as one serve.slo_burn span."""
    import json
    import urllib.request

    from ray_tpu.serve import slo as slo_mod
    from ray_tpu.serve.llm.disagg import build_disagg_app
    from ray_tpu.util import tracing

    slo_mod._reset_watchdog()
    tracing.clear_spans()
    tracing.enable_tracing()
    try:
        handle = serve.run(
            build_disagg_app(model_specs={"base": {"seed": 51, "dim": 8}},
                             decode_replicas=2,
                             prefill_time_per_token_s=0.02,
                             decode_step_time_s=0.01,
                             deployment_prefix="slochaos_"),
            name="slochaos", route_prefix=None)
        dep = "slochaos_LLMFrontend"
        watchdog = slo_mod.get_watchdog()
        watchdog.set_objectives(dep, [slo_mod.SLOObjective(
            name="inter_token_p99_ms", target=0.98, threshold_ms=150.0,
            fast_window_s=8.0, slow_window_s=60.0, burn_threshold=1.0)])

        # Sequential requests: the first eats both handoff faults (two
        # ~0.25s re-prefills fold into one oversized gap), the rest are
        # healthy -- ~1 bad gap in 9 >> the 2% budget.  Streams stay
        # byte-identical through the retries.
        prompt, max_tokens = list(range(12)), 4
        ref = ToyLM(seed=51).reference_generate(prompt, max_tokens)
        for _ in range(3):
            assert _stream(handle, {"prompt": prompt,
                                    "max_tokens": max_tokens}) == ref

        out = watchdog.evaluate()
        row = out[dep]["objectives"]["inter_token_p99_ms"]
        assert row["alerting"], row

        st = serve.status()[f"slochaos#{dep}"]
        assert st["slo"]["alerting"] is True

        from ray_tpu._private.metrics_agent import MetricsAgent
        from ray_tpu._private.runtime import get_runtime

        agent = MetricsAgent(get_runtime())
        try:
            payload = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{agent.port}/api/serve/slo", timeout=10))
            assert payload["deployments"][dep]["alerting"] is True
        finally:
            agent.stop()

        # Recovery: healthy traffic dilutes the fast window (and the bad
        # gap eventually ages out of it) -> asymmetric clear.
        healthy = {"prompt": [5, 6, 7], "max_tokens": 24}
        href = ToyLM(seed=51).reference_generate([5, 6, 7], 24)
        deadline = time.time() + 30
        while time.time() < deadline:
            assert _stream(handle, healthy) == href
            if not watchdog.evaluate()[dep]["alerting"]:
                break
            time.sleep(0.2)
        else:
            pytest.fail("SLO alert never cleared after recovery")
        assert serve.status()[f"slochaos#{dep}"]["slo"]["alerting"] is False

        episodes = [s for s in tracing.exported_spans()
                    if s["name"] == "serve.slo_burn"]
        assert len(episodes) == 1, episodes
        assert episodes[0]["attributes"]["objective"] == "inter_token_p99_ms"
        assert episodes[0]["attributes"]["deployment"] == dep
    finally:
        tracing.disable_tracing()
        tracing.clear_spans()
        slo_mod._reset_watchdog()


# ------------------------------------------------------- reduced-scale bench
@pytest.mark.slow
def test_llm_bench_gate_reduced_scale():
    """ISSUE 11 + 16 acceptance gates via scripts/bench_serve.py --mode
    llm at reduced request count (16 streams as specified): disaggregated
    pools >= 1.5x total tokens/s at equal-or-better inter-token p99, the
    speculative arm >= 1.5x plain decoding at acceptance >= 0.6, and all
    three arms byte-identical (asserted inside run_llm_mode)."""
    spec = importlib.util.spec_from_file_location(
        "bench_serve", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "bench_serve.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    # 3 requests/stream: the smallest scale where the prefill-stall
    # signal dominates the fixed warmup cost (2 sits right at the gate).
    # One median round keeps the slow marker's runtime bounded — the full
    # artifact run (scripts/bench_serve.py --mode llm) uses 3.
    args = argparse.Namespace(llm_streams=16, llm_requests_per_stream=3,
                              llm_ab_rounds=3, llm_median_rounds=1)
    fields = bench.run_llm_mode(args)
    assert fields["llm_disagg_speedup"] >= 1.5, fields
    assert fields["llm_disagg_intertoken_p99_ms"] \
        <= fields["llm_monolithic_intertoken_p99_ms"], fields
    assert fields["llm_disagg_tokens"] == fields["llm_monolithic_tokens"]
    # ISSUE 16 acceptance: speculative decoding beats plain decoding on
    # the identical trace without changing a single byte of output.
    assert fields["llm_spec_speedup"] >= 1.5, fields
    assert fields["llm_spec_acceptance"] >= 0.6, fields
    assert fields["llm_spec_tokens"] == fields["llm_monolithic_tokens"]
    assert fields["llm_spec_speedup_min"] > 0, fields
    # ISSUE 12 acceptance: latency attribution + spans stay within 2%
    # tokens/s of the attribution-off baseline (paired-median A/B inside
    # run_llm_mode; also asserted there before the artifact is written).
    assert fields["llm_attrib_overhead_pct"] <= 2.0, fields
    assert fields["llm_attrib_tokens_per_s_on"] > 0, fields


# ------------------------------------------------ tensor-parallel shards
def test_tp_shard_math_byte_identical():
    """ISSUE 13: context-axis TP sharding — per-rank UNMASKED int64
    partials summed (wraparound ≡ mod 2**64) then masked once in
    token_from_acc must be congruent to the full-context reduction."""
    from ray_tpu.serve.llm.engine import ToyLMShard

    lm = ToyLM(seed=13)
    prompt = [11, 42, 7, 99, 3]
    for tp in (2, 3):
        shards = [ToyLMShard(r, tp, seed=13) for r in range(tp)]
        for s in shards:
            s.reset(prompt)
        out = []
        prev = -1
        for _ in range(12):
            partials = [s.tp_step(prev) for s in shards]
            acc = partials[0]
            for p in partials[1:]:
                acc = acc + p  # int64 wraparound sum, as allreduce does
            toks = {s.token_from_acc(acc) for s in shards}
            assert len(toks) == 1
            prev = toks.pop()
            out.append(prev)
        assert out == lm.reference_generate(prompt, 12), (tp, out)
    # empty-prompt edge: first step reduces over zero owned positions
    shards = [ToyLMShard(r, 2, seed=13) for r in range(2)]
    for s in shards:
        s.reset([])
    tok = shards[0].token_from_acc(shards[0].tp_step(-1)
                                   + shards[1].tp_step(-1))
    assert tok == lm.reference_generate([], 1)[0]


@pytest.mark.slow
def test_tp_inference_example():
    """ISSUE 13 acceptance: examples/serve_tp_inference.py — a 2-rank TP
    serve/llm deployment over compiled allreduce with DeviceChannel
    edges — generates byte-identical to the single-replica oracle (the
    example asserts equality itself; the test gates on its OK line)."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(__file__), "..", "examples",
                          "serve_tp_inference.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, script], env=env,
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    assert "OK" in proc.stdout, proc.stdout
