"""Node-to-node object plane tests: two OS processes, ownership-routed pulls.

The child process (tests/_objxfer_child.py) is the owner node: it runs an
object server and holds the primary copies.  This process is the borrower
node: it resolves each ref's owner address (stamped at pickle time —
ownership-based directory) and pulls the object through the PullManager.
Ref: src/ray/object_manager/object_manager.h:117, pull_manager.h:52.
"""

import base64
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import object_transfer, serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.exceptions import ObjectLostError

CHILD = os.path.join(os.path.dirname(__file__), "_objxfer_child.py")


@pytest.fixture(scope="module")
def owner_node():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_OBJECT_TRANSFER_PULL_TIMEOUT_S"] = "5"
    proc = subprocess.Popen(
        [sys.executable, CHILD], env=env, stdin=subprocess.PIPE,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=False)
    line = proc.stdout.readline().decode()
    assert line.startswith("REFS "), (
        line + proc.stderr.read(4000).decode(errors="replace"))
    refs = serialization.loads(base64.b64decode(line.split()[1]))
    yield refs
    proc.stdin.close()
    proc.wait(timeout=30)


@pytest.fixture()
def borrower():
    ray_tpu.init(ignore_reinit_error=True)
    yield
    # Keep the runtime for the other tests in this module (module-scoped
    # child stays up); individual tests clean their own refs.


def test_pull_small_object(owner_node, borrower):
    val = ray_tpu.get(owner_node["small"], timeout=30)
    assert val == {"kind": "small", "payload": list(range(32))}


def test_pull_large_object_chunked(owner_node, borrower):
    big = ray_tpu.get(owner_node["big"], timeout=60)
    assert isinstance(big, np.ndarray) and big.shape == (6_000_000,)
    assert float(big.sum()) == owner_node["big_sum"]


def test_pull_task_return(owner_node, borrower):
    out = ray_tpu.get(owner_node["task"], timeout=30)
    np.testing.assert_array_equal(out, np.full(1000, 7, dtype=np.int32))


def test_pull_spilled_object_restores(owner_node, borrower):
    spilled = ray_tpu.get(owner_node["spill"], timeout=60)
    assert spilled.shape == (2_000_000,) and spilled[0] == 1.0


def test_remote_ref_as_task_dependency(owner_node, borrower):
    # A remote-owned ref passed as a task arg triggers a dependency pull
    # (the DependencyManager path), not just ray.get.
    @ray_tpu.remote
    def total(x):
        return float(np.sum(x))

    # Re-pickle the ref so the arg carries the owner address even though the
    # local store may already have it cached from earlier tests.
    ref = owner_node["task"]
    assert ray_tpu.get(total.remote(ref), timeout=30) == 7000.0


def test_concurrent_pulls_are_deduplicated(owner_node):
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    rt.store.free(owner_node["big"].id)  # drop the cache to force a re-pull
    before = rt._pull_manager().stats["pulls"]
    results = [None] * 4

    def fetch(i):
        results[i] = ray_tpu.get(owner_node["big"], timeout=60)

    threads = [threading.Thread(target=fetch, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert all(r is not None and r.shape == (6_000_000,) for r in results)
    # One transfer served all four getters.
    assert rt._pull_manager().stats["pulls"] == before + 1


def test_wait_on_remote_ref(owner_node, borrower):
    from ray_tpu._private.runtime import get_runtime

    get_runtime().store.free(owner_node["small"].id)
    ready, pending = ray_tpu.wait([owner_node["small"]], timeout=30)
    assert len(ready) == 1 and not pending


def test_contains_and_push(owner_node, borrower):
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    rt.start_object_server()
    addr = owner_node["addr"]
    ref = ray_tpu.put(np.arange(10))
    rt.store.get_serialized(ref.id)  # materialize wire form
    object_transfer.push(rt.store, ref.id, addr, owner="borrower")
    assert object_transfer.contains(addr, ref.id)
    # And the owner can be asked to drop the pushed cache copy.
    object_transfer.free_remote(addr, ref.id)
    assert not object_transfer.contains(addr, ref.id)


def test_free_remote_refuses_primary_with_live_refs(borrower):
    """ADVICE r2: OP_FREE drops CACHED copies only — a peer must not be able
    to evict a primary copy that still has live local references."""
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    addr = rt.start_object_server()
    ref = ray_tpu.put(np.arange(5))
    rt.store.get_serialized(ref.id)  # materialize wire form
    object_transfer.free_remote(addr, ref.id)  # must be refused
    assert rt.store.contains(ref.id)
    assert list(ray_tpu.get(ref)) == list(range(5))


def test_pull_waits_for_slow_producer(owner_node, borrower):
    # The producing task sleeps past the owner's serve-wait slice, so the
    # borrower sees ST_PENDING and keeps retrying — a long-running producer
    # must not be misreported as object loss (it is merely pending).
    assert ray_tpu.get(owner_node["slow"], timeout=60) == "slow-done"


def test_remote_task_failure_propagates_original_error(owner_node, borrower):
    # The producing task raised ValueError on the owner node; a cross-node
    # get must surface THAT error (task-failure parity), not ObjectLost.
    with pytest.raises(Exception) as ei:
        ray_tpu.get(owner_node["fail"], timeout=30)
    assert "intentional producer failure" in str(ei.value)
    assert not isinstance(ei.value, ObjectLostError)


def test_pull_unknown_object_raises(owner_node, borrower):
    ghost = ObjectRef(ObjectID.from_random(), owner="ghost",
                      owner_addr=owner_node["addr"])
    with pytest.raises(ObjectLostError):
        ray_tpu.get(ghost, timeout=20)


def test_pull_unreachable_owner_raises(borrower):
    ghost = ObjectRef(ObjectID.from_random(), owner="ghost",
                      owner_addr="127.0.0.1:1")  # nothing listens here
    with pytest.raises(ObjectLostError):
        ray_tpu.get(ghost, timeout=10)
