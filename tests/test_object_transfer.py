"""Node-to-node object plane tests: two OS processes, ownership-routed pulls.

The child process (tests/_objxfer_child.py) is the owner node: it runs an
object server and holds the primary copies.  This process is the borrower
node: it resolves each ref's owner address (stamped at pickle time —
ownership-based directory) and pulls the object through the PullManager.
Ref: src/ray/object_manager/object_manager.h:117, pull_manager.h:52.
"""

import base64
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import object_transfer, serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.exceptions import ObjectLostError

CHILD = os.path.join(os.path.dirname(__file__), "_objxfer_child.py")


@pytest.fixture(scope="module")
def owner_node():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAY_TPU_OBJECT_TRANSFER_PULL_TIMEOUT_S"] = "5"
    proc = subprocess.Popen(
        [sys.executable, CHILD], env=env, stdin=subprocess.PIPE,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=False)
    line = proc.stdout.readline().decode()
    assert line.startswith("REFS "), (
        line + proc.stderr.read(4000).decode(errors="replace"))
    refs = serialization.loads(base64.b64decode(line.split()[1]))
    yield refs
    proc.stdin.close()
    proc.wait(timeout=30)


@pytest.fixture()
def borrower():
    ray_tpu.init(ignore_reinit_error=True)
    yield
    # Keep the runtime for the other tests in this module (module-scoped
    # child stays up); individual tests clean their own refs.


def test_pull_small_object(owner_node, borrower):
    val = ray_tpu.get(owner_node["small"], timeout=30)
    assert val == {"kind": "small", "payload": list(range(32))}


def test_pull_large_object_chunked(owner_node, borrower):
    big = ray_tpu.get(owner_node["big"], timeout=60)
    assert isinstance(big, np.ndarray) and big.shape == (6_000_000,)
    assert float(big.sum()) == owner_node["big_sum"]


def test_pull_task_return(owner_node, borrower):
    out = ray_tpu.get(owner_node["task"], timeout=30)
    np.testing.assert_array_equal(out, np.full(1000, 7, dtype=np.int32))


def test_pull_spilled_object_restores(owner_node, borrower):
    spilled = ray_tpu.get(owner_node["spill"], timeout=60)
    assert spilled.shape == (2_000_000,) and spilled[0] == 1.0


def test_remote_ref_as_task_dependency(owner_node, borrower):
    # A remote-owned ref passed as a task arg triggers a dependency pull
    # (the DependencyManager path), not just ray.get.
    @ray_tpu.remote
    def total(x):
        return float(np.sum(x))

    # Re-pickle the ref so the arg carries the owner address even though the
    # local store may already have it cached from earlier tests.
    ref = owner_node["task"]
    assert ray_tpu.get(total.remote(ref), timeout=30) == 7000.0


def test_concurrent_pulls_are_deduplicated(owner_node):
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    rt.store.free(owner_node["big"].id)  # drop the cache to force a re-pull
    before = rt._pull_manager().stats["pulls"]
    results = [None] * 4

    def fetch(i):
        results[i] = ray_tpu.get(owner_node["big"], timeout=60)

    threads = [threading.Thread(target=fetch, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert all(r is not None and r.shape == (6_000_000,) for r in results)
    # One transfer served all four getters.
    assert rt._pull_manager().stats["pulls"] == before + 1


def test_wait_on_remote_ref(owner_node, borrower):
    from ray_tpu._private.runtime import get_runtime

    get_runtime().store.free(owner_node["small"].id)
    ready, pending = ray_tpu.wait([owner_node["small"]], timeout=30)
    assert len(ready) == 1 and not pending


def test_contains_and_push(owner_node, borrower):
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    rt.start_object_server()
    addr = owner_node["addr"]
    ref = ray_tpu.put(np.arange(10))
    rt.store.get_serialized(ref.id)  # materialize wire form
    object_transfer.push(rt.store, ref.id, addr, owner="borrower")
    assert object_transfer.contains(addr, ref.id)
    # And the owner can be asked to drop the pushed cache copy.
    object_transfer.free_remote(addr, ref.id)
    assert not object_transfer.contains(addr, ref.id)


def test_free_remote_refuses_primary_with_live_refs(borrower):
    """ADVICE r2: OP_FREE drops CACHED copies only — a peer must not be able
    to evict a primary copy that still has live local references."""
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    addr = rt.start_object_server()
    ref = ray_tpu.put(np.arange(5))
    rt.store.get_serialized(ref.id)  # materialize wire form
    object_transfer.free_remote(addr, ref.id)  # must be refused
    assert rt.store.contains(ref.id)
    assert list(ray_tpu.get(ref)) == list(range(5))


def test_pull_waits_for_slow_producer(owner_node, borrower):
    # The producing task sleeps past the owner's serve-wait slice, so the
    # borrower sees ST_PENDING and keeps retrying — a long-running producer
    # must not be misreported as object loss (it is merely pending).
    assert ray_tpu.get(owner_node["slow"], timeout=60) == "slow-done"


def test_remote_task_failure_propagates_original_error(owner_node, borrower):
    # The producing task raised ValueError on the owner node; a cross-node
    # get must surface THAT error (task-failure parity), not ObjectLost.
    with pytest.raises(Exception) as ei:
        ray_tpu.get(owner_node["fail"], timeout=30)
    assert "intentional producer failure" in str(ei.value)
    assert not isinstance(ei.value, ObjectLostError)


def test_pull_unknown_object_raises(owner_node, borrower):
    ghost = ObjectRef(ObjectID.from_random(), owner="ghost",
                      owner_addr=owner_node["addr"])
    with pytest.raises(ObjectLostError):
        ray_tpu.get(ghost, timeout=20)


def test_pull_unreachable_owner_raises(borrower):
    ghost = ObjectRef(ObjectID.from_random(), owner="ghost",
                      owner_addr="127.0.0.1:1")  # nothing listens here
    with pytest.raises(ObjectLostError):
        ray_tpu.get(ghost, timeout=10)


# --------------------------------------------------------------------------
# r5 zero-copy plane: same-host arena handoff, sendfile socket path, range
# streams, pooled connections (ref: object_buffer_pool.h zero-copy chunk
# reads, push_manager.h parallel chunked transfer).
# --------------------------------------------------------------------------
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.object_transfer import ObjectTransferServer, PullManager


@pytest.fixture()
def store_pair():
    owner = ObjectStore(capacity_bytes=256 << 20)
    puller = ObjectStore(capacity_bytes=256 << 20)
    server = ObjectTransferServer(lambda: owner)
    pm = PullManager(puller)
    yield owner, puller, server, pm
    server.stop()
    owner.shutdown()
    puller.shutdown()


def _roundtrip(owner, puller, pm, addr, key, value):
    oid = ObjectID(key)
    owner.put(oid, value)
    pm.pull_blocking(oid, addr, timeout=30)
    got = puller.get(oid, timeout=5)
    np.testing.assert_array_equal(got, value)
    return oid


def test_same_host_handoff_engages(store_pair):
    # Same host: the puller maps the owner's arena file and lands the
    # payload with one memcpy — no socket payload bytes at all.
    owner, puller, server, pm = store_pair
    _roundtrip(owner, puller, pm, server.addr, "h1",
               np.arange(1 << 18, dtype=np.float64))
    assert pm.stats["handoffs"] == 1
    assert pm.stats["handoff_bytes"] > (1 << 21)


def test_socket_path_with_handoff_disabled(store_pair):
    # Socket path: server sendfiles from the arena, client lands the bytes
    # straight into a pre-created arena buffer (create_for_receive).
    owner, puller, server, pm = store_pair
    prev = GLOBAL_CONFIG.same_host_handoff
    GLOBAL_CONFIG.same_host_handoff = False
    try:
        _roundtrip(owner, puller, pm, server.addr, "s1",
                   np.arange(1 << 18, dtype=np.float64))
        assert pm.stats["handoffs"] == 0
        assert pm.stats["pulls"] == 1
    finally:
        GLOBAL_CONFIG.same_host_handoff = prev


def test_parallel_range_pull_streams(store_pair):
    # A large object split across concurrent range streams arrives intact.
    owner, puller, server, pm = store_pair
    prev = (GLOBAL_CONFIG.same_host_handoff,
            GLOBAL_CONFIG.parallel_pull_streams,
            GLOBAL_CONFIG.parallel_pull_chunk_bytes)
    GLOBAL_CONFIG.same_host_handoff = False
    GLOBAL_CONFIG.parallel_pull_streams = 3
    GLOBAL_CONFIG.parallel_pull_chunk_bytes = 1 << 20
    try:
        value = np.random.default_rng(0).integers(
            0, 255, size=6 << 20, dtype=np.uint8)  # ~6 MiB -> 6 ranges
        _roundtrip(owner, puller, pm, server.addr, "r1", value)
    finally:
        (GLOBAL_CONFIG.same_host_handoff,
         GLOBAL_CONFIG.parallel_pull_streams,
         GLOBAL_CONFIG.parallel_pull_chunk_bytes) = prev


def test_pooled_connections_reused(store_pair):
    owner, puller, server, pm = store_pair
    for i in range(4):
        _roundtrip(owner, puller, pm, server.addr, f"p{i}",
                   np.full(1024, float(i)))
    # After the pulls, at least one idle connection is parked in the pool
    # and subsequent pulls keep working through it.
    assert any(pool for pool in pm._socks.values())
    _roundtrip(owner, puller, pm, server.addr, "p-again", np.zeros(8))


def test_push_lands_in_receiver_arena(store_pair):
    owner, puller, server, pm = store_pair
    receiver_srv = ObjectTransferServer(lambda: puller)
    try:
        oid = ObjectID("pushed1")
        value = np.arange(1 << 16, dtype=np.int64)
        owner.put(oid, value)
        object_transfer.push(owner, oid, receiver_srv.addr)
        np.testing.assert_array_equal(puller.get(oid, timeout=5), value)
    finally:
        receiver_srv.stop()


def test_push_large_object_partial_sendfile(store_pair):
    # Larger than the socket send buffer: the client socket has a timeout
    # (non-blocking under the hood), so sendfile hits EAGAIN mid-stream and
    # must wait-and-continue — never restart the payload (which would land
    # corrupt bytes).  Regression for the r5 review finding.
    owner, puller, server, pm = store_pair
    receiver_srv = ObjectTransferServer(lambda: puller)
    try:
        oid = ObjectID("pushed-big")
        value = np.random.default_rng(7).integers(
            0, 255, size=32 << 20, dtype=np.uint8)  # 32 MiB
        owner.put(oid, value)
        object_transfer.push(owner, oid, receiver_srv.addr)
        np.testing.assert_array_equal(puller.get(oid, timeout=10), value)
    finally:
        receiver_srv.stop()
