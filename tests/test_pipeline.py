"""Pipeline parallelism (parallel/pipeline.py) + MoE expert parallelism
(models/moe.py) on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt2, moe
from ray_tpu.parallel import (MeshSpec, batch_sharding, make_mesh,
                              pipeline_apply, pytree_sharding)
from ray_tpu.parallel.train_state import create_sharded_state, jit_train_step
from ray_tpu._private.jax_compat import set_mesh


@pytest.fixture(scope="module")
def pipe_mesh():
    return make_mesh(MeshSpec(pipe=4, data=2))


def test_pipeline_matches_sequential(pipe_mesh):
    """pipeline_apply == sequentially applying all layers."""
    key = jax.random.key(0)
    L, D = 8, 16
    w = jax.random.normal(key, (L, D, D)) * 0.1
    x = jax.random.normal(jax.random.key(1), (8, D))

    def stage_fn(local_w, h):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        h, _ = jax.lax.scan(body, h, local_w)
        return h

    expect = stage_fn(w, x)  # all layers in one scan
    with set_mesh(pipe_mesh):
        got = jax.jit(
            lambda w, x: pipeline_apply(stage_fn, w, x, n_microbatches=4)
        )(w, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match(pipe_mesh):
    L, D = 4, 8
    w = jax.random.normal(jax.random.key(0), (L, D, D)) * 0.1
    x = jax.random.normal(jax.random.key(1), (4, D))

    def stage_fn(local_w, h):
        def body(c, wi):
            return jnp.tanh(c @ wi), None
        h, _ = jax.lax.scan(body, h, local_w)
        return h

    def seq_loss(w):
        return jnp.sum(stage_fn(w, x) ** 2)

    def pipe_loss(w):
        return jnp.sum(pipeline_apply(stage_fn, w, x, n_microbatches=2) ** 2)

    g_seq = jax.grad(seq_loss)(w)
    with set_mesh(pipe_mesh):
        g_pipe = jax.jit(jax.grad(pipe_loss))(w)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               rtol=2e-4, atol=2e-4)


def test_gpt2_pipelined_forward_matches_unpipelined():
    mesh = make_mesh(MeshSpec(pipe=2, data=2, tensor=2))
    base = gpt2.GPTConfig(vocab_size=512, n_layer=4, n_head=4, d_model=64,
                          seq_len=32, dtype=jnp.float32, remat=False,
                          attn_impl="xla")
    pp = gpt2.GPTConfig(vocab_size=512, n_layer=4, n_head=4, d_model=64,
                        seq_len=32, dtype=jnp.float32, remat=False,
                        attn_impl="xla", pp_stages=2, pp_microbatches=2)
    params = gpt2.init_params(base, jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 512, (4, 32)), jnp.int32)

    ref = gpt2.forward(params, tokens, base)
    with set_mesh(mesh):
        sharded = jax.device_put(
            params, pytree_sharding(gpt2.logical_axes(pp), mesh))
        got = jax.jit(lambda p, t: gpt2.forward(p, t, pp))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gpt2_pipelined_train_step():
    """Full dp+pp+tp train step: loss decreases over a few steps."""
    mesh = make_mesh(MeshSpec(pipe=2, data=2, tensor=2))
    config = gpt2.GPTConfig(vocab_size=256, n_layer=4, n_head=4, d_model=64,
                            seq_len=32, dtype=jnp.float32, attn_impl="xla",
                            pp_stages=2, pp_microbatches=2)
    opt = gpt2.make_optimizer(1e-2)
    params, opt_state = create_sharded_state(
        lambda k: gpt2.init_params(config, k), gpt2.logical_axes(config),
        mesh, jax.random.key(0), opt)
    step = jit_train_step(gpt2.make_train_step(config, opt), mesh=mesh)
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        jnp.asarray(rng.integers(0, 256, (4, 32)), jnp.int32),
        batch_sharding(mesh))
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


# ------------------------------------------------------------------- MoE/EP
def test_moe_routing_capacity_and_weights():
    config = moe.MoEConfig.tiny()
    x = jax.random.normal(jax.random.key(0), (64, config.d_model))
    w = jax.random.normal(jax.random.key(1),
                          (config.d_model, config.n_experts))
    dispatch, combine, aux = moe._route(x, w, config)
    N, E, C = dispatch.shape
    # No expert over capacity; each token dispatched <= top_k times.
    assert np.all(np.asarray(dispatch.sum(axis=(0, 2))) <= C + 1e-6)
    per_token = np.asarray(dispatch.sum(axis=(1, 2)))
    assert np.all(per_token <= config.top_k + 1e-6)
    # Combine weights of a dispatched token sum to ~1.
    kept = per_token > 0
    csum = np.asarray(combine.sum(axis=(1, 2)))
    np.testing.assert_allclose(csum[kept], 1.0, rtol=1e-5)
    assert float(aux) > 0


def test_moe_forward_and_train_step_expert_parallel():
    mesh = make_mesh(MeshSpec(data=2, expert=4))
    config = moe.MoEConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                           seq_len=32, n_experts=4, expert_mlp=128,
                           dtype=jnp.float32, attn_impl="xla")
    import optax

    opt = optax.adam(1e-2)
    params, opt_state = create_sharded_state(
        lambda k: moe.init_params(config, k), moe.logical_axes(config),
        mesh, jax.random.key(0), opt)
    # Expert weights actually sharded over the expert axis.
    sh = params["blocks"]["expert_in_w"].sharding
    assert "expert" in (sh.spec[1] if isinstance(sh.spec[1], str) else "") \
        or sh.spec[1] == "expert"

    step = jit_train_step(moe.make_train_step(config, opt), mesh=mesh)
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        jnp.asarray(rng.integers(0, 256, (8, 32)), jnp.int32),
        batch_sharding(mesh))
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_expert_parallel_matches_replicated():
    """Same params: EP-sharded forward == unsharded forward."""
    config = moe.MoEConfig(vocab_size=128, n_layer=2, n_head=2, d_model=32,
                           seq_len=16, n_experts=4, expert_mlp=64,
                           dtype=jnp.float32, remat=False, attn_impl="xla")
    params = moe.init_params(config, jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 128, (4, 16)), jnp.int32)
    ref, aux_ref = moe.forward(params, tokens, config)

    mesh = make_mesh(MeshSpec(expert=4, data=2))
    with set_mesh(mesh):
        sharded = jax.device_put(
            params, pytree_sharding(moe.logical_axes(config), mesh))
        got, aux = jax.jit(lambda p, t: moe.forward(p, t, config))(
            sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-4)
