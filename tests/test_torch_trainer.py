"""TorchTrainer: real torch.distributed (gloo) DDP across process-tier
workers (ref: train/torch/torch_trainer.py + tests/test_torch_trainer.py —
multi-worker DDP on one box, gradient sync through the process group).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import RunConfig, ScalingConfig, TorchTrainer


@pytest.fixture(autouse=True)
def _runtime():
    # A fresh runtime with enough CPUs for the 2-worker gang: earlier test
    # modules may leave a 1-CPU runtime behind, and init(ignore_reinit_error)
    # would silently reuse it, failing the PG reservation.
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _loop(config):
    import torch
    import torch.distributed as dist
    from ray_tpu import train
    from ray_tpu.train.torch_trainer import prepare_model

    ctx = train.get_context()
    torch.manual_seed(0)  # identical init on every rank
    model = prepare_model(torch.nn.Linear(4, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    g = torch.Generator().manual_seed(1234 + ctx.get_world_rank())
    x = torch.randn(32, 4, generator=g)
    y = x.sum(dim=1, keepdim=True)
    for step in range(config["steps"]):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()  # DDP allreduces gradients across ranks here
        opt.step()
        w = [p.detach().clone() for p in model.parameters()]
        train.report({
            "step": step, "loss": float(loss),
            "rank": ctx.get_world_rank(),
            "world_size": dist.get_world_size(),
            "weight0": float(w[0].flatten()[0]),
        })


def test_torch_trainer_ddp_two_workers(tmp_path):
    trainer = TorchTrainer(
        _loop, train_loop_config={"steps": 4},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="torch_ddp", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == 3
    assert result.metrics["world_size"] == 2
    assert np.isfinite(result.metrics["loss"])
    assert len(result.metrics_history) == 4  # rank-0 reports


def test_torch_trainer_gradients_actually_sync(tmp_path):
    """Ranks see DIFFERENT data; DDP averaging must keep their weights
    identical after each step.  Every rank writes its final weights to a
    file (the report history keeps rank 0 only), and the test compares the
    two files — a broken allreduce (e.g. prepare_model not wrapping)
    produces different weights and fails."""
    import json

    out_dir = str(tmp_path / "weights")

    def loop(config):
        import json as _json
        import os as _os

        import torch
        from ray_tpu import train
        from ray_tpu.train.torch_trainer import prepare_model

        ctx = train.get_context()
        torch.manual_seed(0)
        model = prepare_model(torch.nn.Linear(3, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        g = torch.Generator().manual_seed(ctx.get_world_rank())
        x = torch.randn(16, 3, generator=g)  # different per rank
        y = x.mean(dim=1, keepdim=True)
        for _ in range(3):
            opt.zero_grad()
            torch.nn.functional.mse_loss(model(x), y).backward()
            opt.step()
        final = torch.cat([p.detach().flatten()
                           for p in model.parameters()])
        _os.makedirs(config["out_dir"], exist_ok=True)
        with open(_os.path.join(config["out_dir"],
                                f"rank{ctx.get_world_rank()}.json"), "w") as f:
            _json.dump(final.tolist(), f)
        train.report({"rank": ctx.get_world_rank()})

    trainer = TorchTrainer(
        loop, train_loop_config={"out_dir": out_dir},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="torch_sync", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    w0 = json.load(open(f"{out_dir}/rank0.json"))
    w1 = json.load(open(f"{out_dir}/rank1.json"))
    np.testing.assert_allclose(w0, w1, rtol=1e-6)

    # Negative control: without DDP the same per-rank data diverges.
    import torch

    def solo(rank):
        torch.manual_seed(0)
        model = torch.nn.Linear(3, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        g = torch.Generator().manual_seed(rank)
        x = torch.randn(16, 3, generator=g)
        y = x.mean(dim=1, keepdim=True)
        for _ in range(3):
            opt.zero_grad()
            torch.nn.functional.mse_loss(model(x), y).backward()
            opt.step()
        return torch.cat([p.detach().flatten()
                          for p in model.parameters()]).tolist()

    assert not np.allclose(solo(0), solo(1)), \
        "control failed: per-rank data too similar to detect sync"
