"""Public exception types (ref: python/ray/exceptions.py)."""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution; re-raised at `get`.

    Carries the remote traceback string (ref: RayTaskError in
    python/ray/exceptions.py) so the user sees where the task failed.
    """

    def __init__(self, cause: BaseException, task_repr: str = "", tb: str = ""):
        self.cause = cause
        self.task_repr = task_repr
        self.remote_traceback = tb or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        super().__init__(f"{task_repr} failed: {cause!r}\nRemote traceback:\n{self.remote_traceback}")

    def __reduce__(self):
        # The default exception protocol would re-call __init__ with the
        # formatted MESSAGE as `cause` (a str), exploding on unpickle —
        # reconstruct from the real fields so errors survive crossing
        # process/node boundaries.
        return (TaskError, (self.cause, self.task_repr, self.remote_traceback))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead: creation failed, it was killed, or it crashed past
    its restart budget (ref: ActorDiedError / gcs_actor_manager.h FSM)."""

    def __init__(self, msg: str = "The actor died", cause: Optional[BaseException] = None):
        self.cause = cause
        super().__init__(msg)


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    """Object value unrecoverable and lineage reconstruction failed
    (ref: ObjectLostError; object_recovery_manager.h:38)."""


class ObjectFreedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id: str = ""):
        super().__init__(f"Task {task_id} was cancelled")


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised when the memory monitor kills a task to avoid host OOM
    (ref: common/memory_monitor.h:52 + worker killing policies)."""
