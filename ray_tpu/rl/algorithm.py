"""Algorithm — the RL control loop, a Tune Trainable.

(ref: rllib/algorithms/algorithm.py:227 Algorithm(Trainable) — step:973 calls
training_step:1780; sampling via EnvRunnerGroup fan-out, learning via
LearnerGroup, weight sync back to runners; save/restore through the
Checkpointable contract.)
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.connectors import ConnectorPipeline, batch_episodes, strip_internal
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rl.utils.metrics import MetricsLogger
from ray_tpu.tune.trainable import Trainable

ENV_RUNNER_RESULTS = "env_runners"
LEARNER_RESULTS = "learners"
EPISODE_RETURN_MEAN = "episode_return_mean"
NUM_ENV_STEPS_SAMPLED_LIFETIME = "num_env_steps_sampled_lifetime"


class Algorithm(Trainable):
    """Base algorithm; subclasses bind a learner class + connector pipeline."""

    learner_class: type = None
    config_class = AlgorithmConfig
    #: Algorithms that implement a multi-agent training_step set this True
    #: (PPO); others fail fast at setup instead of deep inside train().
    supports_multi_agent: bool = False

    # -------------------------------------------------------------- setup
    @classmethod
    def _coerce_config(cls, config) -> AlgorithmConfig:
        """Tune passes plain dicts (param_space), users pass configs —
        one resolution path shared by every algorithm's setup."""
        if isinstance(config, AlgorithmConfig):
            return config
        cfg = cls.config_class()
        base = config.pop("_base_config", None)
        if base is not None:
            cfg = base.copy()
        cfg.update_from_dict(config)
        return cfg

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self._coerce_config(config)
        self.algo_config = cfg
        self.metrics = MetricsLogger()
        self.learner_connector = self.build_learner_connector()
        self._lifetime_steps = 0
        if cfg.is_multi_agent():
            if not type(self).supports_multi_agent:
                raise ValueError(
                    f"{type(self).__name__} does not support multi-agent "
                    f"training; use PPO or drop .multi_agent(...)")
            self._setup_multi_agent(cfg)
            return
        self.module_spec = cfg.module_spec()
        self.env_runner_group = EnvRunnerGroup(
            env=cfg.env, env_config=cfg.env_config,
            module_spec=self.module_spec,
            num_env_runners=cfg.num_env_runners,
            num_envs_per_env_runner=cfg.num_envs_per_env_runner,
            rollout_fragment_length=cfg.rollout_fragment_length,
            explore=cfg.explore, seed=cfg.seed)
        self.learner_group = LearnerGroup(
            learner_class=type(self).learner_class, config=cfg,
            module_spec=self.module_spec, num_learners=cfg.num_learners,
            seed=cfg.seed)
        # Initial weight alignment: runners start from learner params.
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def _setup_multi_agent(self, cfg) -> None:
        """One learner group PER POLICY (independent learning) + a
        multi-agent runner routed by policy_mapping_fn (ref: the reference
        trains a MultiRLModule inside one learner; per-policy groups give
        the same independent-gradient semantics with simpler sharding)."""
        from ray_tpu.rl.env.multi_agent_env_runner import MultiAgentEnvRunner

        self.multi_module_spec = cfg.multi_module_spec()
        runner = MultiAgentEnvRunner(
            env=cfg.env, env_config=cfg.env_config,
            module_spec=self.multi_module_spec,
            policy_mapping_fn=cfg.policy_mapping_fn,
            rollout_fragment_length=cfg.rollout_fragment_length,
            explore=cfg.explore, seed=cfg.seed)
        self.env_runner_group = _SingleRunnerGroup(runner)
        self.learner_groups: Dict[str, LearnerGroup] = {
            pid: LearnerGroup(
                learner_class=type(self).learner_class, config=cfg,
                module_spec=spec, num_learners=cfg.num_learners,
                seed=cfg.seed + i)
            for i, (pid, spec)
            in enumerate(sorted(self.multi_module_spec.module_specs.items()))
        }
        self.env_runner_group.sync_weights(
            {pid: lg.get_weights() for pid, lg in self.learner_groups.items()})

    def build_learner_connector(self) -> ConnectorPipeline:
        return ConnectorPipeline([batch_episodes])

    # --------------------------------------------------------------- step
    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        for runner_metrics in self.env_runner_group.get_metrics():
            if runner_metrics.get("num_episodes", 0) > 0:
                self.metrics.log_dict(runner_metrics, key=ENV_RUNNER_RESULTS,
                                      window=20)
        env_results = self.metrics.reduce(ENV_RUNNER_RESULTS)
        result.setdefault(ENV_RUNNER_RESULTS, {}).update(env_results)
        result[NUM_ENV_STEPS_SAMPLED_LIFETIME] = self._lifetime_steps
        # Flat convenience mirror used by Tune metric= strings.
        if EPISODE_RETURN_MEAN in env_results:
            result[EPISODE_RETURN_MEAN] = env_results[EPISODE_RETURN_MEAN]
        result["time_this_iter_s"] = time.time() - t0
        cfg = self.algo_config
        if cfg.evaluation_interval and self.iteration % cfg.evaluation_interval == 0:
            result["evaluation"] = self.evaluate()
        return result

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # ---------------------------------------------------------- evaluation
    def evaluate(self) -> Dict[str, Any]:
        """Greedy-policy evaluation on a DEDICATED runner (ref: algorithm.py
        evaluate() on eval_env_runner_group).  Training runners must not be
        touched: eval steps would extend their in-progress episodes and feed
        greedy actions (with wrong behavior logps) into the next train batch.
        """
        cfg = self.algo_config
        if not hasattr(self, "_eval_runner"):
            if cfg.is_multi_agent():
                from ray_tpu.rl.env.multi_agent_env_runner import \
                    MultiAgentEnvRunner

                self._eval_runner = MultiAgentEnvRunner(
                    env=cfg.env, env_config=cfg.env_config,
                    module_spec=self.multi_module_spec,
                    policy_mapping_fn=cfg.policy_mapping_fn,
                    rollout_fragment_length=cfg.rollout_fragment_length,
                    explore=False, seed=cfg.seed + 10_000, worker_index=999)
            else:
                from ray_tpu.rl.env.env_runner import SingleAgentEnvRunner

                self._eval_runner = SingleAgentEnvRunner(
                    env=cfg.env, env_config=cfg.env_config,
                    module_spec=self.module_spec,
                    num_envs=cfg.num_envs_per_env_runner,
                    rollout_fragment_length=cfg.rollout_fragment_length,
                    explore=False, seed=cfg.seed + 10_000, worker_index=999)
        self._eval_runner.set_state({"params": self.get_weights()})
        # Fresh episodes every round: a trajectory must not span two policies.
        self._eval_runner.reset()
        episodes = self._eval_runner.sample(
            num_episodes=cfg.evaluation_duration, explore=False)
        returns = [ep.total_return for ep in episodes if ep.is_done]
        if not returns:
            return {}
        return {ENV_RUNNER_RESULTS: {
            EPISODE_RETURN_MEAN: float(np.mean(returns)),
            "num_episodes": len(returns),
        }}

    # -------------------------------------------------------- checkpointing
    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        if self.algo_config.is_multi_agent():
            learner_state = {pid: lg.get_state()
                             for pid, lg in self.learner_groups.items()}
        else:
            learner_state = self.learner_group.get_state()
        state = {
            "learner": learner_state,
            "lifetime_steps": self._lifetime_steps,
        }
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return None

    def load_checkpoint(self, data, checkpoint_dir: str) -> None:
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        if self.algo_config.is_multi_agent():
            for pid, lg in self.learner_groups.items():
                lg.set_state(state["learner"][pid])
            self.env_runner_group.sync_weights(self.get_weights())
        else:
            self.learner_group.set_state(state["learner"])
            self.env_runner_group.sync_weights(self.learner_group.get_weights())
        self._lifetime_steps = state.get("lifetime_steps", 0)

    def cleanup(self) -> None:
        self.env_runner_group.stop()
        if self.algo_config.is_multi_agent():
            for lg in self.learner_groups.values():
                lg.stop()
        else:
            self.learner_group.stop()
        if hasattr(self, "_eval_runner"):
            self._eval_runner.stop()

    # ------------------------------------------------------------- helpers
    def get_weights(self):
        if self.algo_config.is_multi_agent():
            return {pid: lg.get_weights()
                    for pid, lg in self.learner_groups.items()}
        return self.learner_group.get_weights()

    def _sample_batch(self, random_actions: bool = False):
        cfg = self.algo_config
        episodes = self.env_runner_group.sample(
            num_timesteps=cfg.train_batch_size, random_actions=random_actions)
        self._lifetime_steps += sum(
            getattr(ep, "total_env_steps", None) or len(ep)
            for ep in episodes)
        return episodes


class _SingleRunnerGroup:
    """EnvRunnerGroup-shaped adapter over one local runner (the multi-agent
    path; fan-out over remote multi-agent runners composes later the same
    way EnvRunnerGroup wraps SingleAgentEnvRunner)."""

    def __init__(self, runner):
        self.runner = runner

    def sample(self, **kw):
        return self.runner.sample(**kw)

    def sync_weights(self, weights) -> None:
        self.runner.set_state({"params": weights})

    def get_metrics(self) -> List[Dict[str, Any]]:
        return [self.runner.get_metrics()]

    def stop(self) -> None:
        self.runner.stop()
