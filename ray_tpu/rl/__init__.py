"""ray_tpu.rl — reinforcement learning library (new-API-stack shape).

Counterpart of the reference's RLlib (ref: rllib/ — Algorithm on Tune's
Trainable, EnvRunnerGroup sampling, LearnerGroup updates), with the neural
path pure-JAX: RLModules are param pytrees + jitted forwards, learner updates
are single jitted steps, multi-learner gradient sync is a compiled ICI
allreduce instead of torch DDP.
"""

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import (Columns, DefaultActorCritic,
                                       DefaultQModule, RLModule, RLModuleSpec)
from ray_tpu.rl.core.multi_rl_module import MultiRLModule, MultiRLModuleSpec
from ray_tpu.rl.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rl.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rl.env.episode import SingleAgentEpisode
from ray_tpu.rl.env.multi_agent_env import MultiAgentCartPole, MultiAgentEnv
from ray_tpu.rl.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rl.env.multi_agent_episode import MultiAgentEpisode
from ray_tpu.rl.offline import OfflineData, record_episodes

__all__ = [
    "Algorithm", "AlgorithmConfig", "JaxLearner", "LearnerGroup", "Columns",
    "DefaultActorCritic", "DefaultQModule", "RLModule", "RLModuleSpec",
    "SingleAgentEnvRunner", "EnvRunnerGroup", "SingleAgentEpisode",
    "MultiAgentEnv", "MultiAgentCartPole", "MultiAgentEnvRunner",
    "MultiAgentEpisode", "MultiRLModule", "MultiRLModuleSpec",
    "OfflineData", "record_episodes",
]
