"""JaxLearner — jitted gradient updates with ICI gradient sync.

(ref: rllib/core/learner/learner.py:109 Learner — compute_gradients:461,
apply_gradients:604, update_from_batch:967; torch version torch_learner.py:62
wraps the module in DDP `TorchDDPRLModule:409` for NCCL allreduce.)

TPU-native redesign: the whole minibatch update (loss, grad, optimizer) is ONE
jitted function; multi-learner gradient sync is an `allreduce` on the raveled
gradient vector through the XLA collective group (compiled psum over ICI) —
the mirror of TorchLearner's DDP hook, but visible and compiled.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.core.rl_module import Columns, RLModuleSpec


class JaxLearner:
    """Base learner; algorithms override ``compute_loss``."""

    def __init__(self, *, config, module_spec: RLModuleSpec, rank: int = 0,
                 world_size: int = 1, group_name: Optional[str] = None,
                 seed: int = 0):
        self.config = config
        self.module = module_spec.build()
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        self._key = jax.random.key(seed * 31 + rank)
        self.params = self.module.init_params(jax.random.key(seed))
        self.optimizer = self.configure_optimizer()
        self.opt_state = self.optimizer.init(self.params)
        self._update_fn = None
        self._steps = 0
        if world_size > 1 and group_name:
            from ray_tpu import collective

            collective.init_collective_group(world_size, rank, group_name=group_name)

    # ------------------------------------------------------------------
    def configure_optimizer(self) -> optax.GradientTransformation:
        cfg = self.config
        clip = getattr(cfg, "grad_clip", None)
        parts = []
        if clip:
            parts.append(optax.clip_by_global_norm(clip))
        parts.append(optax.adam(getattr(cfg, "lr", 3e-4)))
        return optax.chain(*parts)

    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        """Return (scalar loss, metrics dict). Pure — will be jitted+grad'd."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _build_update(self):
        def step(params, opt_state, batch, key):
            (loss, metrics), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True)(params, batch, key)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        def step_synced(params, opt_state, batch, key):
            # Gradients-only sync: compute local grads jitted, allreduce the
            # raveled vector across the learner group, apply jitted.
            (loss, metrics), grads = self._grad_fn(params, batch, key)
            flat, unravel = jax.flatten_util.ravel_pytree(grads)
            from ray_tpu import collective

            flat = collective.allreduce(flat, group_name=self.group_name,
                                        rank=self.rank) / self.world_size
            grads = unravel(flat)
            params, opt_state, gnorm = self._apply_fn(params, opt_state, grads)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = gnorm
            return params, opt_state, metrics

        if self.world_size <= 1 or not self.group_name:
            self._update_fn = jax.jit(step, donate_argnums=(0, 1))
        else:
            self._grad_fn = jax.jit(
                jax.value_and_grad(self.compute_loss, has_aux=True))

            def apply(params, opt_state, grads):
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state,
                        optax.global_norm(grads))

            self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))
            self._update_fn = step_synced

    # ------------------------------------------------------------------
    def update_from_batch(self, batch: Dict[str, np.ndarray],
                          *, num_epochs: int = 1,
                          minibatch_size: Optional[int] = None) -> Dict[str, Any]:
        """SGD over the batch (ref: learner.py:967 update_from_batch —
        num_epochs/minibatch_size shuffled passes)."""
        if self._update_fn is None:
            self._build_update()
        # Scalars (0-d) ride along whole — e.g. PPO's adaptive kl_coeff —
        # while row arrays are minibatch-sliced.
        rows = {k: v for k, v in batch.items() if np.ndim(v) > 0}
        scalars = {k: v for k, v in batch.items() if np.ndim(v) == 0}
        n = len(next(iter(rows.values())))
        minibatch_size = minibatch_size or n
        all_metrics: List[Dict[str, Any]] = []
        for _ in range(num_epochs):
            # Same permutation on every learner rank (synced collective
            # schedule requires identical minibatch counts).
            perm = np.random.default_rng(self._steps).permutation(n)
            for start in range(0, n, minibatch_size):
                idx = perm[start:start + minibatch_size]
                mb = {k: v[idx] for k, v in rows.items()}
                mb.update(scalars)
                self._key, sub = jax.random.split(self._key)
                self.params, self.opt_state, metrics = self._update_fn(
                    self.params, self.opt_state, mb, sub)
                all_metrics.append(metrics)
            self._steps += 1
        out = {k: float(np.mean([jax.device_get(m[k]) for m in all_metrics]))
               for k in all_metrics[0]}
        self.after_update(out)
        return out

    def after_update(self, metrics: Dict[str, Any]) -> None:
        """Hook (e.g. DQN target-net sync)."""

    # ------------------------------------------------------------------
    def get_weights(self):
        return self.params

    def get_state(self) -> Dict[str, Any]:
        return {"params": self.params, "opt_state": self.opt_state,
                "steps": self._steps}

    def set_state(self, state: Dict[str, Any]) -> None:
        # Copy on receipt: in-process actors share object-store values by
        # reference, and this learner's jitted update DONATES its param/opt
        # buffers — adopting another actor's live arrays would let a later
        # update delete buffers someone else still holds.
        self.params = _copy_tree(state["params"])
        self.opt_state = _copy_tree(state["opt_state"])
        self._steps = state.get("steps", 0)

    def ping(self) -> str:
        return "pong"


def _copy_tree(tree):
    return jax.tree.map(lambda x: jnp.array(x, copy=True)
                        if hasattr(x, "dtype") else x, tree)
