from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import (Columns, DefaultActorCritic,
                                       DefaultQModule, RLModule, RLModuleSpec)

__all__ = ["JaxLearner", "LearnerGroup", "Columns", "DefaultActorCritic",
           "DefaultQModule", "RLModule", "RLModuleSpec"]
