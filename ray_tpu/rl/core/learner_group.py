"""LearnerGroup — one local or N remote learners with compiled gradient sync.

(ref: rllib/core/learner/learner_group.py:80 LearnerGroup — n remote Learner
actors, update() fan-out with batch sharding, get_weights from learner 0.)
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class LearnerGroup:
    def __init__(self, *, learner_class: type, config, module_spec,
                 num_learners: int = 0, seed: int = 0):
        self.num_learners = num_learners
        self._local = None
        self._remote: List[Any] = []
        if num_learners <= 1:
            # In-process learner (ref: learner_group "local mode" when
            # num_learners=0).
            self._local = learner_class(config=config, module_spec=module_spec,
                                        seed=seed)
        else:
            group_name = f"learners-{uuid.uuid4().hex[:8]}"
            cls = ray_tpu.remote(learner_class)
            self._remote = [
                cls.remote(config=config, module_spec=module_spec, rank=r,
                           world_size=num_learners, group_name=group_name,
                           seed=seed)
                for r in range(num_learners)
            ]
            ray_tpu.get([lr.ping.remote() for lr in self._remote])

    # ------------------------------------------------------------------
    def update_from_batch(self, batch: Dict[str, np.ndarray], *,
                          num_epochs: int = 1,
                          minibatch_size: Optional[int] = None) -> Dict[str, Any]:
        """DP-shard the batch over learners; grads allreduce inside each
        learner's update (ref: learner_group.py update_from_batch)."""
        if self._local is not None:
            return self._local.update_from_batch(
                batch, num_epochs=num_epochs, minibatch_size=minibatch_size)
        rows = {k: v for k, v in batch.items() if np.ndim(v) > 0}
        scalars = {k: v for k, v in batch.items() if np.ndim(v) == 0}
        n = len(next(iter(rows.values())))
        world = len(self._remote)
        shard = n // world
        if shard == 0:
            raise ValueError(f"batch of {n} rows cannot shard over {world} learners")
        per_learner_mb = (max(1, minibatch_size // world)
                          if minibatch_size else None)
        refs = []
        for r, learner in enumerate(self._remote):
            # EQUAL shards (up to world-1 remainder rows dropped): every rank
            # must run the identical number of minibatches or the gradient
            # allreduce deadlocks on the odd one out.
            sl = slice(r * shard, (r + 1) * shard)
            sub = {k: v[sl] for k, v in rows.items()}
            sub.update(scalars)
            refs.append(learner.update_from_batch.remote(
                sub, num_epochs=num_epochs, minibatch_size=per_learner_mb))
        results = ray_tpu.get(refs)
        return {k: float(np.mean([m[k] for m in results])) for k in results[0]}

    # ------------------------------------------------------------------
    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._remote[0].get_weights.remote())

    def get_state(self) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._remote[0].get_state.remote())

    def set_state(self, state: Dict[str, Any]) -> None:
        if self._local is not None:
            self._local.set_state(state)
        else:
            ray_tpu.get([lr.set_state.remote(state) for lr in self._remote])

    def foreach_learner(self, fn_name: str, *args, **kwargs) -> List[Any]:
        if self._local is not None:
            return [getattr(self._local, fn_name)(*args, **kwargs)]
        return ray_tpu.get([getattr(lr, fn_name).remote(*args, **kwargs)
                            for lr in self._remote])

    def stop(self) -> None:
        for lr in self._remote:
            try:
                ray_tpu.kill(lr)
            except Exception:
                pass
