"""MultiRLModule — a container of per-policy RLModules.

(ref: rllib/core/rl_module/multi_rl_module.py MultiRLModule — maps module
ids to RLModules; MultiRLModuleSpec builds the container so env runners and
learners construct identical per-policy networks.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

import jax

from ray_tpu.rl.core.rl_module import RLModule, RLModuleSpec


@dataclass(frozen=True)
class MultiRLModuleSpec:
    module_specs: Dict[str, RLModuleSpec] = field(default_factory=dict)

    def build(self) -> "MultiRLModule":
        return MultiRLModule(
            {mid: spec.build() for mid, spec in self.module_specs.items()})


class MultiRLModule:
    """Dict of module_id → RLModule; params are a dict of per-module pytrees."""

    def __init__(self, modules: Dict[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def __contains__(self, module_id: str) -> bool:
        return module_id in self._modules

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def init_params(self, key) -> Dict[str, Any]:
        keys = jax.random.split(key, len(self._modules))
        return {mid: m.init_params(k)
                for (mid, m), k in zip(sorted(self._modules.items()), keys)}
