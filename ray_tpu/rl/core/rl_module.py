"""RLModule — the neural-network component of the RL stack, pure-JAX.

Counterpart of the reference's new-API-stack RLModule
(ref: rllib/core/rl_module/rl_module.py:260 — forward_inference /
forward_exploration / forward_train over a framework-specific network),
redesigned functionally for TPU: a module holds only *static* architecture
config; parameters are a plain pytree created by ``init_params`` and threaded
explicitly through pure ``forward_*`` functions, so the learner can jit/grad
them and shard them over a mesh without framework adapters.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Any  # pytree


class Columns:
    """Batch column names (ref: rllib/core/columns.py Columns)."""

    OBS = "obs"
    ACTIONS = "actions"
    REWARDS = "rewards"
    TERMINATEDS = "terminateds"
    TRUNCATEDS = "truncateds"
    ACTION_LOGP = "action_logp"
    ACTION_DIST_INPUTS = "action_dist_inputs"
    VF_PREDS = "vf_preds"
    ADVANTAGES = "advantages"
    VALUE_TARGETS = "value_targets"
    NEXT_OBS = "next_obs"
    EPS_ID = "eps_id"
    WEIGHTS = "weights"  # importance weights (prioritized replay)


# --------------------------------------------------------------------------
# Action distributions (ref: rllib/models/distributions.py Distribution API)
# --------------------------------------------------------------------------


class Categorical:
    """Discrete distribution over logits."""

    @staticmethod
    def sample(key, logits):
        return jax.random.categorical(key, logits, axis=-1)

    @staticmethod
    def logp(logits, actions):
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(
            logp_all, actions[..., None].astype(jnp.int32), axis=-1
        )[..., 0]

    @staticmethod
    def entropy(logits):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    @staticmethod
    def deterministic(logits):
        return jnp.argmax(logits, axis=-1)


class DiagGaussian:
    """Continuous distribution; dist inputs = concat(mean, log_std)."""

    @staticmethod
    def _split(inputs):
        mean, log_std = jnp.split(inputs, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(key, inputs):
        mean, log_std = DiagGaussian._split(inputs)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    @staticmethod
    def logp(inputs, actions):
        mean, log_std = DiagGaussian._split(inputs)
        var = jnp.exp(2 * log_std)
        return jnp.sum(
            -0.5 * ((actions - mean) ** 2 / var + 2 * log_std + jnp.log(2 * jnp.pi)),
            axis=-1,
        )

    @staticmethod
    def entropy(inputs):
        _, log_std = DiagGaussian._split(inputs)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    @staticmethod
    def deterministic(inputs):
        mean, _ = DiagGaussian._split(inputs)
        return mean


# --------------------------------------------------------------------------
# Module base + default actor-critic
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class RLModuleSpec:
    """(ref: rllib/core/rl_module/rl_module.py:69 RLModuleSpec) — carries the
    module class + ctor config so env runners and learners build identical
    networks from one spec."""

    module_class: type
    observation_dim: int
    action_dim: int
    discrete: bool = True
    model_config: Dict[str, Any] = field(default_factory=dict)

    def build(self) -> "RLModule":
        return self.module_class(
            observation_dim=self.observation_dim,
            action_dim=self.action_dim,
            discrete=self.discrete,
            **self.model_config,
        )


class RLModule:
    """Base module: static config + pure param functions."""

    def __init__(self, observation_dim: int, action_dim: int, discrete: bool = True,
                 **model_config: Any):
        self.observation_dim = observation_dim
        self.action_dim = action_dim
        self.discrete = discrete
        self.model_config = model_config

    # -- to implement
    def init_params(self, key) -> Params:
        raise NotImplementedError

    def forward_train(self, params: Params, obs) -> Dict[str, Any]:
        """Full outputs for the loss (dist inputs + value preds)."""
        raise NotImplementedError

    # -- defaults derived from forward_train
    def forward_inference(self, params: Params, obs) -> Dict[str, Any]:
        return self.forward_train(params, obs)

    def forward_exploration(self, params: Params, obs) -> Dict[str, Any]:
        return self.forward_train(params, obs)

    @property
    def action_dist(self):
        return Categorical if self.discrete else DiagGaussian

    @property
    def dist_input_dim(self) -> int:
        return self.action_dim if self.discrete else 2 * self.action_dim


def _mlp_init(key, sizes: Sequence[int], out_dim: int, in_dim: int,
              out_scale: float = 0.01) -> Dict[str, Any]:
    """Orthogonal-initialized MLP params (tanh torso + linear head)."""
    dims = [in_dim, *sizes]
    layers = []
    orth = jax.nn.initializers.orthogonal
    for i in range(len(dims) - 1):
        key, sub = jax.random.split(key)
        layers.append({
            "w": orth(scale=float(np.sqrt(2.0)))(sub, (dims[i], dims[i + 1]), jnp.float32),
            "b": jnp.zeros((dims[i + 1],), jnp.float32),
        })
    key, sub = jax.random.split(key)
    head = {
        "w": orth(scale=out_scale)(sub, (dims[-1], out_dim), jnp.float32),
        "b": jnp.zeros((out_dim,), jnp.float32),
    }
    return {"layers": layers, "head": head}


def _mlp_apply(params: Dict[str, Any], x):
    for layer in params["layers"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ params["head"]["w"] + params["head"]["b"]


class DefaultActorCritic(RLModule):
    """Separate policy/value MLPs — the default small-obs module
    (ref: rllib/core/rl_module/default_model_config.py DefaultModelConfig,
    fcnet_hiddens=[256,256]; PPO's default torso)."""

    def __init__(self, observation_dim, action_dim, discrete=True,
                 hiddens: Sequence[int] = (64, 64), **kw):
        super().__init__(observation_dim, action_dim, discrete,
                         hiddens=tuple(hiddens), **kw)
        self.hiddens = tuple(hiddens)

    def init_params(self, key) -> Params:
        k_pi, k_vf = jax.random.split(key)
        return {
            "pi": _mlp_init(k_pi, self.hiddens, self.dist_input_dim,
                            self.observation_dim, out_scale=0.01),
            "vf": _mlp_init(k_vf, self.hiddens, 1, self.observation_dim,
                            out_scale=1.0),
        }

    def forward_train(self, params, obs) -> Dict[str, Any]:
        obs = jnp.asarray(obs, jnp.float32)
        return {
            Columns.ACTION_DIST_INPUTS: _mlp_apply(params["pi"], obs),
            Columns.VF_PREDS: _mlp_apply(params["vf"], obs)[..., 0],
        }

    def forward_exploration(self, params, obs) -> Dict[str, Any]:
        obs = jnp.asarray(obs, jnp.float32)
        return {Columns.ACTION_DIST_INPUTS: _mlp_apply(params["pi"], obs)}

    forward_inference = forward_exploration


def conv_out_dim(obs_shape, conv_filters) -> Tuple[int, int, int]:
    """(H, W, C) after a VALID-padded conv stack, validated: a kernel
    outgrowing the shrinking feature map fails HERE with the offending
    layer named, not as an opaque negative-shape error downstream."""
    h, w, c = obs_shape
    for i, (out_c, k, s) in enumerate(conv_filters):
        h = (h - k) // s + 1
        w = (w - k) // s + 1
        c = out_c
        if h <= 0 or w <= 0:
            raise ValueError(
                f"conv_filters[{i}]=({out_c},{k},{s}) shrinks the feature "
                f"map to {h}x{w} for obs_shape {tuple(obs_shape)} — reduce "
                f"kernel/stride or the number of layers")
    return h, w, c


def conv_stack_init(key, obs_shape, conv_filters, init_fn) -> list:
    """Per-layer conv params; ``init_fn(key, shape)`` builds each kernel."""
    convs = []
    in_c = obs_shape[-1]
    for out_c, k, _s in conv_filters:
        key, sub = jax.random.split(key)
        convs.append({"w": init_fn(sub, (k, k, in_c, out_c)),
                      "b": jnp.zeros((out_c,), jnp.float32)})
        in_c = out_c
    return convs


def conv_stack_apply(convs, conv_filters, x, act):
    """NHWC VALID conv stack; returns (N, flattened_features)."""
    for (_out_c, _k, s), layer in zip(conv_filters, convs):
        x = jax.lax.conv_general_dilated(
            x, layer["w"], window_strides=(s, s), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + layer["b"]
        x = act(x)
    return x.reshape((x.shape[0], -1))


class CNNActorCritic(RLModule):
    """Conv encoder + shared-torso actor-critic for PIXEL observations
    (ref: rllib/core/models/configs.py:653 CNNEncoderConfig — the new
    stack's conv encoder; the default Atari torso shape).

    Env runners flatten observations to float32 vectors; this module
    reshapes them back to ``obs_shape`` (H, W, C), scales to [0, 1], runs
    the conv stack on the MXU-friendly NHWC layout, and feeds one shared
    embedding to the policy and value heads (standard for pixel RL —
    separate towers double the conv cost for no measured gain).

    model_config:
      obs_shape      (H, W, C) — required.
      conv_filters   ((out_channels, kernel, stride), ...).
      hiddens        dense widths after flattening.
    """

    def __init__(self, observation_dim, action_dim, discrete=True,
                 obs_shape=None,
                 conv_filters=((16, 4, 2), (32, 3, 1)),
                 hiddens: Sequence[int] = (128,), **kw):
        if obs_shape is None:
            raise ValueError("CNNActorCritic requires model_config["
                             "'obs_shape'] = (H, W, C)")
        super().__init__(observation_dim, action_dim, discrete,
                         obs_shape=tuple(obs_shape),
                         conv_filters=tuple(map(tuple, conv_filters)),
                         hiddens=tuple(hiddens), **kw)
        self.obs_shape = tuple(obs_shape)
        self.conv_filters = tuple(map(tuple, conv_filters))
        self.hiddens = tuple(hiddens)

    def _conv_out_dim(self) -> Tuple[int, int, int]:
        return conv_out_dim(self.obs_shape, self.conv_filters)

    def init_params(self, key) -> Params:
        orth = jax.nn.initializers.orthogonal(scale=float(np.sqrt(2.0)))
        key, k_convs = jax.random.split(key)
        convs = conv_stack_init(
            k_convs, self.obs_shape, self.conv_filters,
            lambda k, shape: orth(k, shape, jnp.float32))
        h, w, c = self._conv_out_dim()
        key, k_torso, k_pi, k_vf = jax.random.split(key, 4)
        torso = _mlp_init(k_torso, self.hiddens[:-1], self.hiddens[-1],
                          h * w * c, out_scale=float(np.sqrt(2.0)))
        return {
            "convs": convs,
            "torso": torso,
            "pi": _mlp_init(k_pi, (), self.dist_input_dim, self.hiddens[-1],
                            out_scale=0.01),
            "vf": _mlp_init(k_vf, (), 1, self.hiddens[-1], out_scale=1.0),
        }

    def _embed(self, params, obs):
        x = jnp.asarray(obs, jnp.float32)
        # Learners batch as (B, T, obs_dim), runners as (N, obs_dim): fold
        # every leading dim into the conv batch, restore after the torso.
        lead = x.shape[:-1]
        x = x.reshape((-1, *self.obs_shape)) / 255.0
        x = conv_stack_apply(params["convs"], self.conv_filters, x,
                             jax.nn.relu)
        z = jax.nn.relu(_mlp_apply(params["torso"], x))
        return z.reshape((*lead, z.shape[-1]))

    def forward_train(self, params, obs) -> Dict[str, Any]:
        z = self._embed(params, obs)
        return {
            Columns.ACTION_DIST_INPUTS: _mlp_apply(params["pi"], z),
            Columns.VF_PREDS: _mlp_apply(params["vf"], z)[..., 0],
        }

    def forward_exploration(self, params, obs) -> Dict[str, Any]:
        z = self._embed(params, obs)
        return {Columns.ACTION_DIST_INPUTS: _mlp_apply(params["pi"], z)}

    forward_inference = forward_exploration


class DefaultQModule(RLModule):
    """Q-network module for DQN (ref: rllib/algorithms/dqn/default_dqn_rl_module.py).

    Params hold both the online and target networks; the learner updates the
    target copy on its own schedule.
    """

    def __init__(self, observation_dim, action_dim, discrete=True,
                 hiddens: Sequence[int] = (64, 64), **kw):
        assert discrete, "DQN requires a discrete action space"
        super().__init__(observation_dim, action_dim, discrete,
                         hiddens=tuple(hiddens), **kw)
        self.hiddens = tuple(hiddens)

    def init_params(self, key) -> Params:
        q = _mlp_init(key, self.hiddens, self.action_dim, self.observation_dim,
                      out_scale=0.01)
        return {"q": q, "target_q": jax.tree.map(jnp.copy, q)}

    def forward_train(self, params, obs) -> Dict[str, Any]:
        obs = jnp.asarray(obs, jnp.float32)
        q = _mlp_apply(params["q"], obs)
        return {"q_values": q, Columns.ACTION_DIST_INPUTS: q}

    def forward_target(self, params, obs):
        obs = jnp.asarray(obs, jnp.float32)
        return _mlp_apply(params["target_q"], obs)

    forward_inference = forward_train
    forward_exploration = forward_train
