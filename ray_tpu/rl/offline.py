"""Offline RL data path — record experience, read it back as train batches.

(ref: rllib/offline/ — offline_data.py OfflineData reads Ray Data datasets
of episodes/transitions and feeds learner batches; output writers in
rllib/offline/output_writer.py record env-runner experience.)

TPU-native shape: transitions are flat numpy columns (the learner's native
batch format), stored via ray_tpu.data (parquet/json), and sampled as
uniform minibatches host-side — device work stays in the learner's jitted
update.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu.rl.connectors import episodes_to_transitions
from ray_tpu.rl.core.rl_module import Columns


def record_episodes(episodes, path: str, *, format: str = "parquet") -> str:
    """Write episodes as flat transition rows (offline training input).

    (ref: rllib/offline/output_writer.py / `config.output` recording.)
    """
    import pandas as pd

    import ray_tpu.data as rdata

    cols = episodes_to_transitions(episodes)
    n = len(cols[Columns.OBS])
    rows: Dict[str, Any] = {}
    for k, v in cols.items():
        if v.ndim > 1:
            # Arrow-friendly: multi-dim columns become lists per row.
            rows[k] = [v[i].tolist() for i in range(n)]
        else:
            rows[k] = v.tolist()
    df = pd.DataFrame(rows)
    ds = rdata.from_pandas(df)
    os.makedirs(path, exist_ok=True)
    if format == "parquet":
        ds.write_parquet(path)
    elif format == "json":
        ds.write_json(path)
    else:
        raise ValueError(f"unsupported offline format: {format}")
    return path


class OfflineData:
    """Uniformly samples learner batches from a recorded dataset
    (ref: rllib/offline/offline_data.py OfflineData / OfflinePreLearner).

    Accepts a path (parquet/json dir), a ray_tpu.data Dataset, or an
    in-memory column dict.  Materializes to numpy columns once — offline
    datasets for control tasks fit host memory; larger corpora can pass a
    Dataset and stream via ``iter_batches`` instead.
    """

    def __init__(self, source: Union[str, Dict[str, np.ndarray], Any],
                 *, format: str = "parquet", seed: int = 0):
        self._rng = np.random.default_rng(seed)
        if isinstance(source, dict):
            self.columns = {k: np.asarray(v) for k, v in source.items()}
        else:
            if isinstance(source, str):
                import ray_tpu.data as rdata

                ds = (rdata.read_parquet(source) if format == "parquet"
                      else rdata.read_json(source))
            else:
                ds = source
            rows = ds.take_all()
            if not rows:
                raise ValueError("offline dataset is empty")
            keys = rows[0].keys()
            self.columns = {
                k: np.asarray([r[k] for r in rows]) for k in keys}
        for k in (Columns.OBS, Columns.ACTIONS):
            if k not in self.columns:
                raise ValueError(f"offline data missing column {k!r}")
        self.columns = {k: np.asarray(v, np.float32)
                        if np.asarray(v).dtype == np.float64 else np.asarray(v)
                        for k, v in self.columns.items()}
        self.size = len(self.columns[Columns.OBS])

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self.size, batch_size)
        return {k: v[idx] for k, v in self.columns.items()}
