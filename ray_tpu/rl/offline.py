"""Offline RL data path — record experience, read it back as train batches.

(ref: rllib/offline/ — offline_data.py OfflineData reads Ray Data datasets
of episodes/transitions and feeds learner batches; output writers in
rllib/offline/output_writer.py record env-runner experience.)

TPU-native shape: transitions are flat numpy columns (the learner's native
batch format), stored via ray_tpu.data (parquet/json), and sampled as
uniform minibatches host-side — device work stays in the learner's jitted
update.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu.rl.connectors import episodes_to_transitions
from ray_tpu.rl.core.rl_module import Columns


def record_episodes(episodes, path: str, *, format: str = "parquet") -> str:
    """Write episodes as flat transition rows (offline training input).

    (ref: rllib/offline/output_writer.py / `config.output` recording.)
    """
    import pandas as pd

    import ray_tpu.data as rdata

    cols = episodes_to_transitions(episodes)
    n = len(cols[Columns.OBS])
    rows: Dict[str, Any] = {}
    for k, v in cols.items():
        if v.ndim > 1:
            # Arrow-friendly: multi-dim columns become lists per row.
            rows[k] = [v[i].tolist() for i in range(n)]
        else:
            rows[k] = v.tolist()
    df = pd.DataFrame(rows)
    ds = rdata.from_pandas(df)
    os.makedirs(path, exist_ok=True)
    if format == "parquet":
        ds.write_parquet(path)
    elif format == "json":
        ds.write_json(path)
    else:
        raise ValueError(f"unsupported offline format: {format}")
    return path


class StreamingColumnsError(AttributeError, ValueError):
    """`columns` was accessed on a streaming OfflineData.  AttributeError
    ancestry keeps hasattr()/getattr(default) probes working; ValueError
    ancestry keeps it catchable as the config error it really is."""


class OfflineData:
    """Uniformly samples learner batches from a recorded dataset
    (ref: rllib/offline/offline_data.py OfflineData / OfflinePreLearner).

    Accepts a path (parquet/json dir), a ray_tpu.data Dataset, or an
    in-memory column dict.  Two modes:

    * **materialized** (default): numpy columns once, exact uniform
      sampling — right for control-task corpora that fit host memory.
    * **streaming=True**: the dataset-scale path (ref: offline_data.py's
      streaming OfflinePreLearner) — blocks stream through the data
      pipeline's distributed shuffle, and ``sample`` draws from a bounded
      in-memory window that continuously refills, so the corpus never
      materializes on one host.
    """

    def __init__(self, source: Union[str, Dict[str, np.ndarray], Any],
                 *, format: str = "parquet", seed: int = 0,
                 streaming: bool = False, window_rows: int = 50_000):
        self._rng = np.random.default_rng(seed)
        self._stream = None
        if isinstance(source, dict):
            self.columns = {k: np.asarray(v) for k, v in source.items()}
        else:
            if isinstance(source, str):
                import ray_tpu.data as rdata

                ds = (rdata.read_parquet(source) if format == "parquet"
                      else rdata.read_json(source))
            else:
                ds = source
            if streaming:
                self._init_streaming(ds, window_rows)
                return
            rows = ds.take_all()
            if not rows:
                raise ValueError("offline dataset is empty")
            keys = rows[0].keys()
            self.columns = {
                k: np.asarray([r[k] for r in rows]) for k in keys}
        for k in (Columns.OBS, Columns.ACTIONS):
            if k not in self.columns:
                raise ValueError(f"offline data missing column {k!r}")
        self.columns = {k: np.asarray(v, np.float32)
                        if np.asarray(v).dtype == np.float64 else np.asarray(v)
                        for k, v in self.columns.items()}
        self.size = len(self.columns[Columns.OBS])

    # ------------------------------------------------------------ streaming
    def _init_streaming(self, ds, window_rows: int) -> None:
        self._base_ds = ds  # epochs reshuffle FROM HERE (chaining shuffle
        #                     ops onto the shuffled result would re-execute
        #                     every prior epoch's shuffle)
        self._window_rows = window_rows
        self._window: dict = {}
        self._cursor = 0
        self.size = None  # unknown without a full pass — by design
        self._stream = self._batches()
        self._refill(1)
        for k in (Columns.OBS, Columns.ACTIONS):
            if k not in self._window:
                raise ValueError(f"offline data missing column {k!r}")

    def _batches(self):
        while True:  # epoch loop: a fresh shuffle of the BASE dataset
            shuffled = self._base_ds.random_shuffle(
                seed=int(self._rng.integers(1 << 30)))
            got_any = False
            for batch in shuffled.iter_batches(batch_size=4096):
                got_any = True
                yield batch
            if not got_any:
                raise ValueError("offline dataset is empty")

    @property
    def is_streaming(self) -> bool:
        return self._stream is not None

    def has_column(self, name: str) -> bool:
        return name in (self._window if self._stream is not None
                        else self.columns)

    def __getattr__(self, name: str):
        # Only reached when normal lookup fails — i.e. streaming mode, where
        # `columns` is never materialized.  Algorithms that derive returns
        # over the whole dataset (MARWIL) would otherwise die with an opaque
        # AttributeError deep in setup.  The error subclasses AttributeError
        # so hasattr()/getattr(..., default) feature probes keep working.
        if name == "columns":
            raise StreamingColumnsError(
                "this OfflineData is streaming (streaming=True): full-dataset "
                "columns are never materialized. Algorithms that need whole-"
                "dataset returns derivation (e.g. MARWIL without a 'returns' "
                "column) require streaming=False, or precompute 'returns' in "
                "the dataset.")
        raise AttributeError(name)

    def _remaining(self) -> int:
        if not self._window:
            return 0
        return len(next(iter(self._window.values()))) - self._cursor

    def _refill(self, need: int) -> None:
        """Compact the unconsumed tail, append stream batches up to the
        window target, then shuffle ONCE — sample() just advances a cursor
        (O(batch) per draw, not O(window))."""
        target = max(self._window_rows, need)
        parts: Dict[str, list] = {}
        total = self._remaining()
        for k, v in self._window.items():
            parts[k] = [v[self._cursor:]]
        while total < target:
            batch = next(self._stream)
            total += len(next(iter(batch.values())))
            for k, v in batch.items():
                v = np.asarray(v)
                if v.dtype == np.float64:
                    v = v.astype(np.float32)
                parts.setdefault(k, []).append(v)
        window = {k: np.concatenate(vs) if len(vs) > 1 else vs[0]
                  for k, vs in parts.items()}
        order = self._rng.permutation(total)
        self._window = {k: v[order] for k, v in window.items()}
        self._cursor = 0

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        if self._stream is None:
            idx = self._rng.integers(0, self.size, batch_size)
            return {k: v[idx] for k, v in self.columns.items()}
        if self._remaining() < batch_size:
            self._refill(batch_size)
        start = self._cursor
        self._cursor += batch_size
        return {k: v[start:self._cursor] for k, v in self._window.items()}
