"""Connector pipelines — episodes → train batch.

(ref: rllib/connectors/ — env_to_module/, learner/, module_to_env/ pipelines;
the learner pipeline's GAE piece lives in
rllib/connectors/learner/general_advantage_estimation.py.)

Host-side data munging stays in numpy (it's control-plane glue, not MXU
work); anything per-minibatch-hot lives inside the learner's jitted update.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.rl.core.rl_module import Columns
from ray_tpu.rl.env.episode import SingleAgentEpisode


class ConnectorPipeline:
    """Ordered list of callables batch=fn(batch, episodes)."""

    def __init__(self, connectors: Optional[Sequence[Callable]] = None):
        self.connectors: List[Callable] = list(connectors or [])

    def append(self, connector: Callable) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def __call__(self, batch: Dict[str, Any], episodes: List[SingleAgentEpisode],
                 **kw) -> Dict[str, Any]:
        for c in self.connectors:
            batch = c(batch, episodes, **kw)
        return batch


def batch_episodes(batch: Dict[str, Any], episodes: List[SingleAgentEpisode],
                   **kw) -> Dict[str, Any]:
    """Default learner connector head: concatenate per-step columns.

    obs excludes each episode's final observation (it has no action); the
    final obs is kept separately for bootstrapping.
    """
    obs, actions, rewards, logp, terms, eps_bounds, last_obs = \
        [], [], [], [], [], [], []
    start = 0
    for ep in episodes:
        T = len(ep)
        arr = ep.to_numpy()
        obs.append(arr["obs"][:-1])
        last_obs.append(arr["obs"][-1])
        actions.append(arr["actions"])
        rewards.append(arr["rewards"])
        if Columns.ACTION_LOGP in arr:
            logp.append(arr[Columns.ACTION_LOGP])
        terms.append(ep.is_terminated)
        eps_bounds.append((start, start + T))
        start += T
    batch = dict(batch)
    batch[Columns.OBS] = np.concatenate(obs).astype(np.float32)
    batch[Columns.ACTIONS] = np.concatenate(actions)
    batch[Columns.REWARDS] = np.concatenate(rewards).astype(np.float32)
    if logp:
        batch[Columns.ACTION_LOGP] = np.concatenate(logp).astype(np.float32)
    batch["_eps_bounds"] = eps_bounds
    batch["_eps_terminated"] = terms
    batch["_last_obs"] = np.stack(last_obs).astype(np.float32)
    return batch


class GeneralAdvantageEstimation:
    """GAE(λ) learner connector (ref: rllib/connectors/learner/
    general_advantage_estimation.py — runs the module's value head over the
    episodes, computes advantages + value targets)."""

    def __init__(self, gamma: float = 0.99, lambda_: float = 0.95,
                 normalize_advantages: bool = True):
        self.gamma = gamma
        self.lambda_ = lambda_
        self.normalize = normalize_advantages

    def __call__(self, batch: Dict[str, Any], episodes, *, module=None,
                 params=None, vf_fn=None, **kw) -> Dict[str, Any]:
        assert vf_fn is not None, "GAE needs the learner's jitted value fn"
        values = np.asarray(vf_fn(params, batch[Columns.OBS]))
        bootstrap = np.asarray(vf_fn(params, batch["_last_obs"]))
        advantages = np.zeros_like(batch[Columns.REWARDS])
        vtargets = np.zeros_like(advantages)
        for i, (s, e) in enumerate(batch["_eps_bounds"]):
            v_next = 0.0 if batch["_eps_terminated"][i] else float(bootstrap[i])
            lastgaelam = 0.0
            for t in range(e - 1, s - 1, -1):
                delta = (batch[Columns.REWARDS][t] + self.gamma * v_next
                         - values[t])
                lastgaelam = delta + self.gamma * self.lambda_ * lastgaelam
                advantages[t] = lastgaelam
                v_next = values[t]
            vtargets[s:e] = advantages[s:e] + values[s:e]
        if self.normalize and len(advantages) > 1:
            advantages = (advantages - advantages.mean()) / (advantages.std() + 1e-8)
        batch[Columns.ADVANTAGES] = advantages.astype(np.float32)
        batch[Columns.VALUE_TARGETS] = vtargets.astype(np.float32)
        batch[Columns.VF_PREDS] = values.astype(np.float32)
        return batch


def strip_internal(batch: Dict[str, Any], episodes=None, **kw) -> Dict[str, Any]:
    """Drop host-side bookkeeping columns before the jitted update."""
    return {k: v for k, v in batch.items() if not k.startswith("_")}


def episodes_to_transitions(episodes: List[SingleAgentEpisode]) -> Dict[str, np.ndarray]:
    """(obs, action, reward, next_obs, done) rows for replay buffers (DQN)."""
    obs, actions, rewards, next_obs, dones, truncs = [], [], [], [], [], []
    for ep in episodes:
        arr = ep.to_numpy()
        T = len(ep)
        obs.append(arr["obs"][:-1])
        next_obs.append(arr["obs"][1:])
        actions.append(arr["actions"])
        rewards.append(arr["rewards"])
        d = np.zeros(T, np.float32)
        if ep.is_terminated:
            d[-1] = 1.0
        dones.append(d)
        # Truncation marks an episode BOUNDARY without a terminal state —
        # offline consumers (MARWIL returns-to-go) must not let value
        # bootstraps/returns bleed across it.
        t = np.zeros(T, np.float32)
        if ep.is_truncated:
            t[-1] = 1.0
        truncs.append(t)
    return {
        Columns.OBS: np.concatenate(obs).astype(np.float32),
        Columns.ACTIONS: np.concatenate(actions),
        Columns.REWARDS: np.concatenate(rewards).astype(np.float32),
        Columns.NEXT_OBS: np.concatenate(next_obs).astype(np.float32),
        Columns.TERMINATEDS: np.concatenate(dones),
        Columns.TRUNCATEDS: np.concatenate(truncs),
    }
