"""AlgorithmConfig — fluent builder.

(ref: rllib/algorithms/algorithm_config.py:103 AlgorithmConfig — chained
.environment()/.env_runners()/.training()/.learners()/.evaluation() setters,
`build_algo()`, and dict round-trip for Tune param_space merging.)
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type, Union

from ray_tpu.rl.core.rl_module import DefaultActorCritic, RLModuleSpec


class AlgorithmConfig:
    algo_class: Optional[type] = None  # set by subclasses

    def __init__(self, algo_class: Optional[type] = None):
        if algo_class is not None:
            self.algo_class = algo_class
        # environment
        self.env: Union[str, Callable, None] = None
        self.env_config: Dict[str, Any] = {}
        # env runners
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 200
        self.explore = True
        # training
        self.lr = 3e-4
        self.gamma = 0.99
        self.grad_clip: Optional[float] = None
        self.train_batch_size = 4000
        self.minibatch_size: Optional[int] = 128
        self.num_epochs = 1
        self.model: Dict[str, Any] = {}
        self.module_class: type = DefaultActorCritic
        # learners
        self.num_learners = 0
        # multi-agent (ref: algorithm_config.py multi_agent(policies=...,
        # policy_mapping_fn=...))
        self.policies: Optional[Dict[str, Optional[RLModuleSpec]]] = None
        self.policy_mapping_fn: Callable[[str], str] = \
            lambda agent_id: "default_policy"
        # debug / misc
        self.seed = 0
        self.evaluation_interval: Optional[int] = None
        self.evaluation_duration = 5  # episodes

    # ------------------------------------------------------------- setters
    def environment(self, env=None, *, env_config: Optional[Dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    explore: Optional[bool] = None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore is not None:
            self.explore = explore
        return self

    def training(self, **kwargs: Any) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"Unknown training config key: {k}")
            setattr(self, k, v)
        return self

    def learners(self, *, num_learners: Optional[int] = None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def rl_module(self, *, module_class: Optional[type] = None,
                  model_config: Optional[Dict] = None) -> "AlgorithmConfig":
        if module_class is not None:
            self.module_class = module_class
        if model_config is not None:
            self.model = dict(model_config)
        return self

    def multi_agent(self, *, policies: Optional[Dict[str, Any]] = None,
                    policy_mapping_fn: Optional[Callable[[str], str]] = None
                    ) -> "AlgorithmConfig":
        """Declare per-policy modules + the agent→policy routing
        (ref: algorithm_config.py:multi_agent).  ``policies`` maps policy id
        to an RLModuleSpec, or None to derive the spec from the env's
        per-agent spaces."""
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def is_multi_agent(self) -> bool:
        return bool(self.policies)

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_duration: Optional[int] = None) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # ------------------------------------------------------------- build
    def module_spec(self) -> RLModuleSpec:
        from ray_tpu.rl.env.env_runner import env_spaces

        obs_dim, act_dim, discrete = env_spaces(self.env, self.env_config)
        return RLModuleSpec(module_class=self.module_class,
                            observation_dim=obs_dim, action_dim=act_dim,
                            discrete=discrete, model_config=dict(self.model))

    def multi_module_spec(self):
        """Per-policy module specs, deriving unspecified ones from the env's
        per-agent spaces (ref: MultiRLModuleSpec construction in
        algorithm_config.get_multi_rl_module_spec)."""
        import numpy as np

        from ray_tpu.rl.core.multi_rl_module import MultiRLModuleSpec

        assert self.is_multi_agent()
        env = self.env(self.env_config) if callable(self.env) else self.env
        try:
            import gymnasium as gym

            specs: Dict[str, RLModuleSpec] = {}
            for pid, spec in self.policies.items():
                if spec is not None:
                    specs[pid] = spec
                    continue
                agent = next(
                    (a for a in env.possible_agents
                     if self.policy_mapping_fn(a) == pid), None)
                assert agent is not None, \
                    f"no agent maps to policy {pid!r}; pass an explicit spec"
                ospace = env.observation_spaces[agent]
                aspace = env.action_spaces[agent]
                discrete = isinstance(aspace, gym.spaces.Discrete)
                specs[pid] = RLModuleSpec(
                    module_class=self.module_class,
                    observation_dim=int(np.prod(ospace.shape)),
                    action_dim=(int(aspace.n) if discrete
                                else int(np.prod(aspace.shape))),
                    discrete=discrete, model_config=dict(self.model))
            return MultiRLModuleSpec(specs)
        finally:
            if callable(self.env):
                env.close()

    def build_algo(self):
        assert self.algo_class is not None, "config has no algo_class bound"
        return self.algo_class(config=self)

    # alias kept for reference API parity
    build = build_algo

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    # ------------------------------------------------------------- dict io
    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in vars(self).items() if not k.startswith("_")}

    def update_from_dict(self, d: Dict[str, Any]) -> "AlgorithmConfig":
        for k, v in d.items():
            if k == "env":
                self.env = v
            elif hasattr(self, k):
                setattr(self, k, v)
            else:
                raise AttributeError(f"Unknown config key: {k}")
        return self
