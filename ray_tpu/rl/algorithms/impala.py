"""IMPALA — async actor-learner with V-trace off-policy correction.

(ref: rllib/algorithms/impala/impala.py:135-197 — async sample fan-out with
in-flight request tracking + AggregatorActors; V-trace loss in
rllib/algorithms/impala/torch/impala_torch_learner.py, vtrace math in
rllib/algorithms/impala/torch/vtrace_torch.py; Espeholt et al. 2018.)

The env runners sample continuously (one in-flight request each); the driver
drains whichever finish first (`wait`), aggregates fragments into train
batches, and updates the learner while the next samples are already running —
behavior-policy logps ride along for the V-trace correction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.rl_module import Columns


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_pg_rho_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rollout_fragment_length = 50
        self.train_batch_size = 500
        self.num_epochs = 1
        self.minibatch_size = None
        self.broadcast_interval = 1  # weight sync every N updates
        self.max_requests_in_flight_per_env_runner = 2
        #: Aggregation actors between runners and learner (ref:
        #: impala.py:135-197 AggregatorActor): fragments are stitched into
        #: train batches OFF the learner loop, and weight broadcasts go
        #: async — the driver only routes refs.  0 = aggregate inline.
        self.num_aggregator_actors = 0


def vtrace(behavior_logp, target_logp, rewards, values, bootstrap_value,
           discounts, clip_rho: float = 1.0, clip_pg_rho: float = 1.0,
           mask=None):
    """V-trace targets over one trajectory (T,) — lax.scan from the tail
    (ref: vtrace_torch.py multi_from_logits, single-agent form).  ``mask``
    zeroes padded steps' deltas so they can't perturb real steps."""
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = jnp.minimum(1.0, rhos)
    values_next = jnp.concatenate([values[1:], bootstrap_value[None]])
    deltas = clipped_rhos * (rewards + discounts * values_next - values)
    if mask is not None:
        deltas = deltas * mask

    def backward(acc, t):
        acc = deltas[t] + discounts[t] * cs[t] * acc
        return acc, acc

    T = rewards.shape[0]
    _, vs_minus_v = jax.lax.scan(backward, jnp.zeros(()), jnp.arange(T - 1, -1, -1))
    vs_minus_v = vs_minus_v[::-1]
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]])
    pg_rhos = jnp.minimum(clip_pg_rho, rhos)
    pg_advantages = pg_rhos * (rewards + discounts * vs_next - values)
    return vs, pg_advantages


class IMPALALearner(JaxLearner):
    def _vtrace_terms(self, params, batch: Dict[str, Any]):
        """Shared V-trace machinery (also the base of APPO's loss): forward
        pass, masked normalizer, vmapped V-trace over the fragment axis.
        Padded steps have discount 0 AND masked deltas, so nothing leaks
        backward through the scan into real steps."""
        cfg = self.config
        out = self.module.forward_train(params, batch[Columns.OBS])
        dist = self.module.action_dist
        inputs = out[Columns.ACTION_DIST_INPUTS]
        target_logp = dist.logp(inputs, batch[Columns.ACTIONS])
        values = out[Columns.VF_PREDS]
        mask = batch["mask"]
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        vs, pg_adv = jax.vmap(
            lambda blp, tlp, r, v, bv, d, m: vtrace(
                blp, tlp, r, v, bv, d,
                cfg.vtrace_clip_rho_threshold,
                cfg.vtrace_clip_pg_rho_threshold, mask=m)
        )(batch[Columns.ACTION_LOGP], target_logp, batch[Columns.REWARDS],
          values, batch["bootstrap_value"], batch["discounts"], mask)
        return (dist, inputs, target_logp, values, mask, denom,
                jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv))

    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        cfg = self.config
        (dist, inputs, target_logp, values, mask, denom, vs, pg_adv) = \
            self._vtrace_terms(params, batch)
        policy_loss = -jnp.sum(target_logp * pg_adv * mask) / denom
        value_loss = 0.5 * jnp.sum(jnp.square(values - vs) * mask) / denom
        entropy = jnp.sum(dist.entropy(inputs) * mask) / denom
        total = (policy_loss + cfg.vf_loss_coeff * value_loss
                 - cfg.entropy_coeff * entropy)
        return total, {"policy_loss": policy_loss, "vf_loss": value_loss,
                       "entropy": entropy}


def build_vtrace_batch(episodes, T: int, gamma: float) -> Dict[str, np.ndarray]:
    """Chunk fragments into (B, T) rows for the vmapped V-trace.

    Fragments longer than T are SPLIT into multiple rows (never discarded);
    short rows are zero-padded and masked out of the loss.  Module-level so
    aggregation actors run it off the learner loop (ref: impala.py:135-197
    AggregatorActor)."""
    cols: Dict[str, List] = {k: [] for k in
                             (Columns.OBS, Columns.ACTIONS, Columns.REWARDS,
                              Columns.ACTION_LOGP, "discounts", "mask",
                              "bootstrap_obs", "bootstrap_terminated")}
    for ep in episodes:
        arr = ep.to_numpy()
        t = len(ep)
        for start in range(0, t, T):
            end = min(start + T, t)
            n = end - start
            pad = T - n

            def padded(x, value=0.0):
                x = x[start:end]
                if pad:
                    x = np.concatenate([x, np.full((pad, *x.shape[1:]),
                                                   value, x.dtype)])
                return x

            cols[Columns.OBS].append(padded(arr["obs"][:-1]))
            cols[Columns.ACTIONS].append(padded(arr["actions"]))
            cols[Columns.REWARDS].append(padded(arr["rewards"]))
            cols[Columns.ACTION_LOGP].append(padded(arr[Columns.ACTION_LOGP]))
            terminal_chunk = ep.is_terminated and end == t
            disc = np.full(n, gamma, np.float32)
            if terminal_chunk:
                disc[-1] = 0.0
            if pad:
                disc = np.concatenate([disc, np.zeros(pad, np.float32)])
            cols["discounts"].append(disc)
            mask = np.concatenate([np.ones(n, np.float32),
                                   np.zeros(pad, np.float32)])
            cols["mask"].append(mask)
            cols["bootstrap_obs"].append(arr["obs"][end])
            cols["bootstrap_terminated"].append(
                1.0 if terminal_chunk else 0.0)
    return {k: np.stack(v).astype(np.float32) if k != Columns.ACTIONS
            else np.stack(v)
            for k, v in cols.items()}


class BatchAggregator:
    """Aggregation actor: buffers episode fragments, emits a train batch
    once enough steps accumulated (ref: impala.py:135-197 AggregatorActor +
    aggregator_actor.py — the tier that keeps episode stitching off the
    learner loop)."""

    def __init__(self, T: int, gamma: float, train_batch_size: int):
        self._T = T
        self._gamma = gamma
        self._target = train_batch_size
        self._buf: List[Any] = []
        self._steps = 0

    def add(self, episodes) -> Any:
        """Returns a ready (B, T) batch dict, or None while accumulating."""
        live = [ep for ep in episodes if len(ep) > 0]
        self._buf.extend(live)
        self._steps += sum(len(ep) for ep in live)
        if self._steps < self._target:
            return None
        episodes, self._buf, self._steps = self._buf, [], 0
        return build_vtrace_batch(episodes, self._T, self._gamma)


class IMPALA(Algorithm):
    learner_class = IMPALALearner
    config_class = IMPALAConfig

    def setup(self, config) -> None:
        super().setup(config)
        cfg = self.algo_config
        self._inflight: Dict[Any, Any] = {}  # ref -> runner
        self._updates = 0
        self._aggregators: List[Any] = []
        self._agg_rr = 0
        self._pending_batches: List[Any] = []
        if cfg.num_aggregator_actors and self.env_runner_group.runners:
            agg_cls = ray_tpu.remote(BatchAggregator)
            self._aggregators = [
                agg_cls.remote(cfg.rollout_fragment_length, cfg.gamma,
                               cfg.train_batch_size)
                for _ in range(cfg.num_aggregator_actors)]

    def _batch_from_episodes(self, episodes) -> Dict[str, np.ndarray]:
        cfg = self.algo_config
        return build_vtrace_batch(episodes, cfg.rollout_fragment_length,
                                  cfg.gamma)

    def cleanup(self) -> None:
        for agg in self._aggregators:
            try:
                ray_tpu.kill(agg)
            except Exception:
                pass
        self._aggregators = []
        super().cleanup()

    def _saturate_runners(self) -> None:
        """Keep every runner loaded with in-flight sample requests."""
        cfg = self.algo_config
        runners = self.env_runner_group.runners
        per = max(cfg.rollout_fragment_length,
                  cfg.train_batch_size // len(runners))
        for r in runners:
            inflight_for_r = sum(1 for v in self._inflight.values() if v is r)
            while inflight_for_r < cfg.max_requests_in_flight_per_env_runner:
                self._inflight[r.sample.remote(num_timesteps=per)] = r
                inflight_for_r += 1

    def training_step(self) -> Dict[str, Any]:
        runners = self.env_runner_group.runners
        if not runners:
            # Synchronous fallback (num_env_runners=0): plain on-policy step.
            episodes = self._sample_batch()
            return {"learners": self._learn(episodes)}
        self._saturate_runners()
        if self._aggregators:
            return self._aggregated_step()

        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=30.0)
        episodes = []
        for ref in ready:
            self._inflight.pop(ref, None)
            episodes.extend(ray_tpu.get(ref))
        self._lifetime_steps += sum(len(ep) for ep in episodes)
        return {"learners": self._learn(episodes),
                "num_inflight_requests": len(self._inflight)}

    def _aggregated_step(self) -> Dict[str, Any]:
        """Aggregator pipeline: the driver only ROUTES refs — finished
        sample refs go to aggregation actors (round-robin), ready batches
        go to the learner, weight broadcasts are fire-and-forget (ref:
        impala.py:135-197 — sampling, aggregation and learning overlap)."""
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                timeout=30.0)
        # Drain EVERY completed ref, not just the first: undrained refs
        # count toward the in-flight cap, so leaving them parked while a
        # learner update runs stalls the runners the cap governs.
        more, _ = ray_tpu.wait(list(self._inflight),
                               num_returns=len(self._inflight), timeout=0)
        for ref in dict.fromkeys(list(ready) + list(more)):
            if self._inflight.pop(ref, None) is None:
                continue
            agg = self._aggregators[self._agg_rr % len(self._aggregators)]
            self._agg_rr += 1
            # The episode payload flows runner -> aggregator; the driver
            # never materializes it.
            self._pending_batches.append(agg.add.remote(ref))
        self._saturate_runners()  # samplers never idle while we learn

        per_batch: List[Dict[str, Any]] = []
        if self._pending_batches:
            done, self._pending_batches = ray_tpu.wait(
                self._pending_batches,
                num_returns=len(self._pending_batches), timeout=0.02)
            for bref in done:
                batch = ray_tpu.get(bref)
                if batch is None:
                    continue  # aggregator still accumulating
                self._lifetime_steps += int(batch["mask"].sum())
                per_batch.append(self._learn_from_batch(batch))
        if len(per_batch) > 1:
            # Mean over this step's updates — returning only the last batch
            # would bias reported losses toward a subsample.
            results = {}
            for k in set().union(*per_batch):
                vals = []
                for r in per_batch:
                    try:
                        vals.append(float(r[k]))
                    except (KeyError, TypeError, ValueError):
                        pass
                if vals:
                    # Mean of the batches that reported it — a metric
                    # logged conditionally still averages, not "last wins".
                    results[k] = float(np.mean(vals))
                else:
                    # Non-scalar metric (array/nested): pass the LAST value
                    # through so the key's schema stays stable across steps
                    # instead of vanishing whenever >1 batch completed.
                    for r in reversed(per_batch):
                        if k in r:
                            results[k] = r[k]
                            break
        else:
            results = per_batch[0] if per_batch else {}
        return {"learners": results,
                "num_inflight_requests": len(self._inflight),
                "num_pending_agg_batches": len(self._pending_batches),
                "num_batches_learned": len(per_batch)}

    def _learn(self, episodes) -> Dict[str, Any]:
        episodes = [ep for ep in episodes if len(ep) > 0]
        if not episodes:
            return {}
        return self._learn_from_batch(self._batch_from_episodes(episodes))

    def _learn_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        cfg = self.algo_config
        # Bootstrap values from current params (host-side, jitted).
        if self.learner_group._local is not None:
            learner = self.learner_group._local
            params = learner.params
            module = learner.module
        else:
            params = self.learner_group.get_weights()
            module = self.module_spec.build()
        if not hasattr(self, "_vf_fn"):
            self._vf_fn = jax.jit(
                lambda p, o: module.forward_train(p, o)[Columns.VF_PREDS])
        bv = np.asarray(self._vf_fn(params, batch.pop("bootstrap_obs")))
        batch["bootstrap_value"] = (bv * (1.0 - batch.pop("bootstrap_terminated"))
                                    ).astype(np.float32)
        batch = self._augment_batch(batch)  # subclass hook (APPO's kl_coeff)
        results = self.learner_group.update_from_batch(
            batch, num_epochs=cfg.num_epochs)
        self._after_learn(results)
        self._updates += 1
        if self._updates % cfg.broadcast_interval == 0:
            # Fire-and-forget under the aggregator pipeline: actor mailbox
            # order guarantees a runner applies the weights before its next
            # sample call; blocking would stall the learner loop.
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights(),
                block=not self._aggregators)
        return results

    def _augment_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return batch

    def _after_learn(self, results: Dict[str, Any]) -> None:
        pass
