"""PPO — Proximal Policy Optimization, new-API-stack shape.

(ref: rllib/algorithms/ppo/ppo.py PPOConfig/PPO; loss in
rllib/algorithms/ppo/torch/ppo_torch_learner.py — clipped surrogate +
clipped value loss + entropy bonus; north-star workload
tuned_examples/ppo/cartpole_ppo.py reaching default_reward=450.)
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.connectors import (ConnectorPipeline, GeneralAdvantageEstimation,
                                   batch_episodes, strip_internal)
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.rl_module import Columns


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 3e-4
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 1.0
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.use_gae = True
        self.lambda_ = 0.95
        self.num_epochs = 6
        self.minibatch_size = 128
        self.train_batch_size = 4000
        self.normalize_advantages = True


class PPOLearner(JaxLearner):
    def __init__(self, **kw):
        super().__init__(**kw)

        def vf(params, obs):
            return self.module.forward_train(params, obs)[Columns.VF_PREDS]

        self.vf_fn = jax.jit(vf)

    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        cfg = self.config
        out = self.module.forward_train(params, batch[Columns.OBS])
        dist = self.module.action_dist
        inputs = out[Columns.ACTION_DIST_INPUTS]
        logp = dist.logp(inputs, batch[Columns.ACTIONS])
        logp_ratio = jnp.exp(logp - batch[Columns.ACTION_LOGP])
        advantages = batch[Columns.ADVANTAGES]

        surrogate = jnp.minimum(
            advantages * logp_ratio,
            advantages * jnp.clip(logp_ratio, 1 - cfg.clip_param,
                                  1 + cfg.clip_param))
        policy_loss = -jnp.mean(surrogate)

        vf_preds = out[Columns.VF_PREDS]
        vf_targets = batch[Columns.VALUE_TARGETS]
        vf_loss = jnp.square(vf_preds - vf_targets)
        vf_loss_clipped = jnp.clip(vf_loss, 0, cfg.vf_clip_param)
        value_loss = jnp.mean(vf_loss_clipped)

        entropy = jnp.mean(dist.entropy(inputs))
        # Approx KL(old || new) (ref: ppo_torch_learner.py mean_kl_loss);
        # penalized with the ADAPTIVE kl coefficient the algorithm threads
        # through the batch (a 0-d array, so adapting it doesn't recompile).
        kl = jnp.mean(batch[Columns.ACTION_LOGP] - logp)
        kl_coeff = batch.get("kl_coeff", jnp.float32(0.0))

        total = (policy_loss + cfg.vf_loss_coeff * value_loss
                 - cfg.entropy_coeff * entropy
                 + kl_coeff * jnp.maximum(kl, 0.0))
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": value_loss,
            "entropy": entropy,
            "mean_kl": kl,
        }


class PPO(Algorithm):
    learner_class = PPOLearner
    config_class = PPOConfig
    supports_multi_agent = True

    def build_learner_connector(self) -> ConnectorPipeline:
        cfg = self.algo_config
        return ConnectorPipeline([
            batch_episodes,
            GeneralAdvantageEstimation(
                gamma=cfg.gamma, lambda_=cfg.lambda_,
                normalize_advantages=cfg.normalize_advantages),
            strip_internal,
        ])

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        if cfg.is_multi_agent():
            return self._multi_agent_training_step()
        episodes = self._sample_batch()
        # GAE uses current learner params; local learner exposes vf_fn
        # directly, remote groups bootstrap with learner-0 params through the
        # same jitted fn built on the driver's module copy.
        if self.learner_group._local is not None:
            vf_fn = self.learner_group._local.vf_fn
            params = self.learner_group._local.params
        else:
            if not hasattr(self, "_driver_vf"):
                module = self.module_spec.build()

                def vf(params, obs):
                    return module.forward_train(params, obs)[Columns.VF_PREDS]

                self._driver_vf = jax.jit(vf)
            vf_fn = self._driver_vf
            params = self.learner_group.get_weights()
        batch = self.learner_connector({}, episodes, params=params, vf_fn=vf_fn)
        if not hasattr(self, "_kl_coeff"):
            self._kl_coeff = float(cfg.kl_coeff)
        batch["kl_coeff"] = np.float32(self._kl_coeff)
        learner_results = self.learner_group.update_from_batch(
            batch, num_epochs=cfg.num_epochs, minibatch_size=cfg.minibatch_size)
        # Adaptive KL coefficient (ref: ppo.py after_train_step — double
        # when kl overshoots 2x target, halve when under 0.5x).
        kl = learner_results.get("mean_kl")
        if kl is not None and cfg.kl_coeff > 0:
            if kl > 2.0 * cfg.kl_target:
                self._kl_coeff *= 1.5
            elif kl < 0.5 * cfg.kl_target:
                self._kl_coeff *= 0.5
            learner_results["curr_kl_coeff"] = self._kl_coeff
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {"learners": learner_results}

    def _multi_agent_training_step(self) -> Dict[str, Any]:
        """Independent PPO per policy: route each agent's trajectories to its
        module's learner group, update all policies, sync all weights
        (ref: multi-agent PPO via MultiRLModule in the reference's learner;
        independent learning is its default multi-agent regime)."""
        cfg = self.algo_config
        ma_episodes = self._sample_batch()
        by_module: Dict[str, list] = {}
        for ma_ep in ma_episodes:
            for mid, eps in ma_ep.episodes_by_module().items():
                by_module.setdefault(mid, []).extend(eps)
        if not hasattr(self, "_kl_coeffs"):
            self._kl_coeffs = {mid: float(cfg.kl_coeff)
                               for mid in self.learner_groups}
        results: Dict[str, Any] = {}
        for mid, episodes in by_module.items():
            group = self.learner_groups[mid]
            learner = group._local
            assert learner is not None, \
                "multi-agent PPO currently drives local (in-process) " \
                "learner groups; set num_learners=0"
            batch = self.learner_connector(
                {}, episodes, params=learner.params, vf_fn=learner.vf_fn)
            batch["kl_coeff"] = np.float32(self._kl_coeffs[mid])
            res = group.update_from_batch(
                batch, num_epochs=cfg.num_epochs,
                minibatch_size=cfg.minibatch_size)
            kl = res.get("mean_kl")
            if kl is not None and cfg.kl_coeff > 0:
                if kl > 2.0 * cfg.kl_target:
                    self._kl_coeffs[mid] *= 1.5
                elif kl < 0.5 * cfg.kl_target:
                    self._kl_coeffs[mid] *= 0.5
            results[mid] = res
        self.env_runner_group.sync_weights(
            {mid: g.get_weights() for mid, g in self.learner_groups.items()})
        return {"learners": results}
