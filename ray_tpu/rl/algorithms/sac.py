"""SAC — soft actor-critic for continuous control.

(ref: rllib/algorithms/sac/sac.py SACConfig/SAC; losses in
rllib/algorithms/sac/torch/sac_torch_learner.py — twin-Q TD target with
entropy bonus, squashed-Gaussian actor loss, auto-tuned temperature alpha;
soft target sync with tau.)

TPU-native redesign: the whole update (critic + actor + alpha + soft target
sync) is ONE jitted function over a structured param pytree with three optax
optimizers; per-section gradients use closures that rebuild the full dict so
stop-gradient boundaries are explicit rather than relying on separate
backward passes.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.connectors import episodes_to_transitions
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.rl_module import (Columns, RLModule, _mlp_apply,
                                       _mlp_init)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SquashedGaussian:
    """tanh-squashed Gaussian scaled to the action range.

    (ref: rllib/models/torch/torch_distributions.py TorchSquashedGaussian.)
    Instance-based (unlike the static Categorical/DiagGaussian) because the
    action scale is part of the distribution.
    """

    def __init__(self, scale: float = 1.0):
        self.scale = scale

    def _split(self, inputs):
        mean, log_std = jnp.split(inputs, 2, axis=-1)
        return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample(self, key, inputs):
        mean, log_std = self._split(inputs)
        pre = mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)
        return jnp.tanh(pre) * self.scale

    def sample_with_logp(self, key, inputs):
        """One pass returning (action, logp) — the learner's hot path."""
        mean, log_std = self._split(inputs)
        pre = mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)
        act = jnp.tanh(pre)
        logp = self._logp_pre(inputs, pre, act)
        return act * self.scale, logp

    def _logp_pre(self, inputs, pre, tanh_pre):
        mean, log_std = self._split(inputs)
        var = jnp.exp(2 * log_std)
        base = -0.5 * ((pre - mean) ** 2 / var + 2 * log_std
                       + jnp.log(2 * jnp.pi))
        # tanh change-of-variables + the constant scale factor.
        correction = jnp.log(1.0 - tanh_pre ** 2 + 1e-6) + jnp.log(self.scale)
        return jnp.sum(base - correction, axis=-1)

    def logp(self, inputs, actions):
        squashed = jnp.clip(actions / self.scale, -1.0 + 1e-6, 1.0 - 1e-6)
        pre = jnp.arctanh(squashed)
        return self._logp_pre(inputs, pre, squashed)

    def entropy(self, inputs):
        # No closed form for the squashed distribution; the Gaussian entropy
        # is the standard surrogate (alpha auto-tuning uses -logp anyway).
        _, log_std = self._split(inputs)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    def deterministic(self, inputs):
        mean, _ = self._split(inputs)
        return jnp.tanh(mean) * self.scale


class SACModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics (+ target copies)
    (ref: rllib/algorithms/sac/default_sac_rl_module.py)."""

    def __init__(self, observation_dim, action_dim, discrete=False,
                 hiddens=(256, 256), action_scale: float = 1.0, **kw):
        assert not discrete, "SAC is a continuous-control algorithm"
        super().__init__(observation_dim, action_dim, discrete,
                         hiddens=tuple(hiddens), action_scale=action_scale,
                         **kw)
        self.hiddens = tuple(hiddens)
        self.action_scale = action_scale

    @property
    def action_dist(self):
        return SquashedGaussian(self.action_scale)

    def init_params(self, key):
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        sa_dim = self.observation_dim + self.action_dim
        q1 = _mlp_init(k_q1, self.hiddens, 1, sa_dim, out_scale=1.0)
        q2 = _mlp_init(k_q2, self.hiddens, 1, sa_dim, out_scale=1.0)
        return {
            "pi": _mlp_init(k_pi, self.hiddens, 2 * self.action_dim,
                            self.observation_dim, out_scale=0.01),
            "q1": q1, "q2": q2,
            "target_q1": jax.tree.map(jnp.copy, q1),
            "target_q2": jax.tree.map(jnp.copy, q2),
            "log_alpha": jnp.zeros((), jnp.float32),
        }

    def forward_train(self, params, obs) -> Dict[str, Any]:
        obs = jnp.asarray(obs, jnp.float32)
        return {Columns.ACTION_DIST_INPUTS: _mlp_apply(params["pi"], obs)}

    forward_exploration = forward_train
    forward_inference = forward_train

    def q_values(self, q_params, obs, actions):
        sa = jnp.concatenate(
            [jnp.asarray(obs, jnp.float32), jnp.asarray(actions, jnp.float32)],
            axis=-1)
        return _mlp_apply(q_params, sa)[..., 0]


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.module_class = SACModule
        self.lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.train_batch_size = 256
        self.num_epochs = 1
        self.minibatch_size = None
        self.rollout_fragment_length = 1
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.tau = 0.005  # soft target sync every update
        self.target_entropy: Any = "auto"  # auto => -action_dim
        self.initial_alpha = 1.0
        self.n_step = 1
        self.updates_per_step = 1


class SACLearner(JaxLearner):
    """Three-optimizer jitted update; overrides the base single-loss path."""

    def __init__(self, **kw):
        super().__init__(**kw)
        if self.world_size > 1:
            # The three-optimizer step below has no gradient-allreduce hook;
            # installing it silently would let multi-learner SAC/CQL diverge
            # per-rank (each updating on its own shard) — fail fast instead.
            raise NotImplementedError(
                "SAC/CQL multi-learner gradient sync is not implemented; "
                "use num_learners<=1 (PPO/DQN/IMPALA support learner groups)")
        cfg = self.config
        self._target_entropy = (
            -float(self.module.action_dim)
            if cfg.target_entropy == "auto" else float(cfg.target_entropy))
        self.params = dict(self.params)
        self.params["log_alpha"] = jnp.asarray(
            np.log(cfg.initial_alpha), jnp.float32)
        # Section optimizers (ref: sac.py optimizer per network).
        self._opt_pi = optax.adam(cfg.lr)
        self._opt_q = optax.adam(cfg.critic_lr)
        self._opt_alpha = optax.adam(cfg.alpha_lr)
        self.opt_state = {
            "pi": self._opt_pi.init(self.params["pi"]),
            "q": self._opt_q.init((self.params["q1"], self.params["q2"])),
            "alpha": self._opt_alpha.init(self.params["log_alpha"]),
        }

    def _build_update(self):
        cfg = self.config
        module = self.module
        dist = module.action_dist
        tau = cfg.tau
        gamma = cfg.gamma
        target_entropy = self._target_entropy
        opt_pi, opt_q, opt_alpha = self._opt_pi, self._opt_q, self._opt_alpha

        def step(params, opt_state, batch, key):
            obs = batch[Columns.OBS]
            actions = batch[Columns.ACTIONS]
            rewards = batch[Columns.REWARDS]
            next_obs = batch[Columns.NEXT_OBS]
            dones = batch[Columns.TERMINATEDS]
            k_next, k_new = jax.random.split(key)
            alpha = jnp.exp(params["log_alpha"])

            # ---- critic update (twin Q, entropy-regularized TD target) ----
            next_inputs = module.forward_train(params, next_obs)[
                Columns.ACTION_DIST_INPUTS]
            next_act, next_logp = dist.sample_with_logp(k_next, next_inputs)
            q_next = jnp.minimum(
                module.q_values(params["target_q1"], next_obs, next_act),
                module.q_values(params["target_q2"], next_obs, next_act))
            target = jax.lax.stop_gradient(
                rewards + (gamma ** cfg.n_step) * (1.0 - dones)
                * (q_next - alpha * next_logp))

            cur_inputs = module.forward_train(params, obs)[
                Columns.ACTION_DIST_INPUTS]
            k_pen, k_next = jax.random.split(k_next)

            def critic_loss_fn(q_pair):
                q1p, q2p = q_pair
                q1 = module.q_values(q1p, obs, actions)
                q2 = module.q_values(q2p, obs, actions)
                td = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)
                # Subclass hook (CQL's conservative penalty); 0.0 for SAC.
                return td + self.critic_penalty(
                    q1p, q2p, obs, actions, cur_inputs, k_pen)

            q_pair = (params["q1"], params["q2"])
            critic_loss, q_grads = jax.value_and_grad(critic_loss_fn)(q_pair)
            q_updates, opt_q_state = opt_q.update(q_grads, opt_state["q"], q_pair)
            q1_new, q2_new = optax.apply_updates(q_pair, q_updates)

            # ---- actor update (uses UPDATED critics, frozen) --------------
            def actor_loss_fn(pi_params):
                inputs = _mlp_apply(pi_params, jnp.asarray(obs, jnp.float32))
                new_act, new_logp = dist.sample_with_logp(k_new, inputs)
                q_min = jnp.minimum(
                    module.q_values(jax.lax.stop_gradient(q1_new), obs, new_act),
                    module.q_values(jax.lax.stop_gradient(q2_new), obs, new_act))
                return jnp.mean(alpha * new_logp - q_min), new_logp

            (actor_loss, new_logp), pi_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(params["pi"])
            pi_updates, opt_pi_state = opt_pi.update(
                pi_grads, opt_state["pi"], params["pi"])
            pi_new = optax.apply_updates(params["pi"], pi_updates)

            # ---- temperature update (ref: sac.py target entropy loss) -----
            def alpha_loss_fn(log_alpha):
                return -jnp.mean(jnp.exp(log_alpha) * jax.lax.stop_gradient(
                    new_logp + target_entropy))

            alpha_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(
                params["log_alpha"])
            a_update, opt_a_state = opt_alpha.update(
                a_grad, opt_state["alpha"], params["log_alpha"])
            log_alpha_new = optax.apply_updates(params["log_alpha"], a_update)

            # ---- soft target sync (every update, tau-averaged) ------------
            soft = lambda t, o: (1.0 - tau) * t + tau * o
            params = {
                "pi": pi_new, "q1": q1_new, "q2": q2_new,
                "target_q1": jax.tree.map(soft, params["target_q1"], q1_new),
                "target_q2": jax.tree.map(soft, params["target_q2"], q2_new),
                "log_alpha": log_alpha_new,
            }
            opt_state = {"pi": opt_pi_state, "q": opt_q_state,
                         "alpha": opt_a_state}
            metrics = {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "alpha_loss": alpha_loss, "alpha": jnp.exp(log_alpha_new),
                "q_target_mean": jnp.mean(target),
                "entropy_est": -jnp.mean(new_logp),
                "total_loss": critic_loss + actor_loss + alpha_loss,
            }
            return params, opt_state, metrics

        self._update_fn = jax.jit(step, donate_argnums=(0, 1))

    def critic_penalty(self, q1p, q2p, obs, actions, dist_inputs, key):
        """Extra (jax-pure) critic loss term; CQL overrides with its
        conservative regularizer."""
        return 0.0

    def get_weights(self):
        # Runners only need the actor head (plus scale config lives in the
        # module); shipping critic/target copies every sync wastes bandwidth.
        return {"pi": self.params["pi"]}


class SAC(Algorithm):
    learner_class = SACLearner
    config_class = SACConfig

    def setup(self, config) -> None:
        super().setup(config)
        from ray_tpu.rl.utils.replay_buffers import ReplayBuffer

        self.replay = ReplayBuffer(self.algo_config.replay_buffer_capacity,
                                   seed=self.algo_config.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        warmup = (self._lifetime_steps
                  < cfg.num_steps_sampled_before_learning_starts)
        episodes = self.env_runner_group.sample(
            num_timesteps=(cfg.num_steps_sampled_before_learning_starts
                           if warmup else
                           cfg.rollout_fragment_length
                           * max(1, cfg.num_envs_per_env_runner)),
            random_actions=warmup)
        self._lifetime_steps += sum(len(ep) for ep in episodes)
        self.replay.add(episodes_to_transitions(episodes))
        if warmup or len(self.replay) < cfg.train_batch_size:
            return {"learners": {}, "replay_size": len(self.replay)}
        results: Dict[str, Any] = {}
        for _ in range(max(1, cfg.updates_per_step)):
            batch = self.replay.sample(cfg.train_batch_size)
            results = self.learner_group.update_from_batch(batch)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {"learners": results, "replay_size": len(self.replay)}
