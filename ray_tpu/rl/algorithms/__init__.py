from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig, DQNLearner
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig, IMPALALearner
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig, PPOLearner

__all__ = ["PPO", "PPOConfig", "PPOLearner", "DQN", "DQNConfig", "DQNLearner",
           "IMPALA", "IMPALAConfig", "IMPALALearner"]
