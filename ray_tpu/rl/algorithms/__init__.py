from ray_tpu.rl.algorithms.appo import APPO, APPOConfig, APPOLearner
from ray_tpu.rl.algorithms.bc import BC, BCConfig, BCLearner
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig, CQLLearner
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig, DQNLearner
from ray_tpu.rl.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig, IMPALALearner
from ray_tpu.rl.algorithms.marwil import MARWIL, MARWILConfig, MARWILLearner
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig, PPOLearner
from ray_tpu.rl.algorithms.sac import SAC, SACConfig, SACLearner

__all__ = ["APPO", "APPOConfig", "APPOLearner",
           "PPO", "PPOConfig", "PPOLearner", "DQN", "DQNConfig", "DQNLearner",
           "IMPALA", "IMPALAConfig", "IMPALALearner",
           "SAC", "SACConfig", "SACLearner", "BC", "BCConfig", "BCLearner",
           "CQL", "CQLConfig", "CQLLearner",
           "MARWIL", "MARWILConfig", "MARWILLearner",
           "DreamerV3", "DreamerV3Config"]
