"""DreamerV3 — model-based RL: an RSSM world model trained on replayed
sequences, with actor and critic trained entirely in imagination.

(ref: rllib/algorithms/dreamerv3/ — dreamerv3.py config/algorithm,
torch/dreamerv3_torch_learner.py world-model + actor + critic losses,
utils/summaries.py; Hafner et al. 2023.)

Compact JAX redesign, same architecture spine, deliberate reductions
(documented so the parity line is honest):

* RSSM with categorical latents (S groups x C classes), straight-through
  gradients, 1% unimix; GRU deterministic path.
* World-model loss: symlog-MSE reconstruction, TWOHOT symlog
  distributional reward head (ref: tf/dreamerv3_tf_learner.py:398-405 +
  reward_predictor_layer.py — 255 buckets over symlog [-20, 20],
  zero-initialized output layer), Bernoulli continue, KL balancing
  (beta_dyn 0.5 / beta_rep 0.1) with 1-nat free bits.
* Actor-critic on imagined rollouts: lambda-returns (gamma 0.997,
  lambda 0.95), TWOHOT distributional critic (cross-entropy to the
  twohot-encoded symlog lambda-return) with a slow EMA target for
  bootstrapping, REINFORCE actor with return-range normalization (EMA
  of the 5th-95th percentile span) and entropy bonus.
* Vector observations use an MLP encoder; PIXEL observations
  (``config.obs_shape=(H, W, C)``) route through the shared conv stack
  (core/rl_module.py) with the DreamerV3 [-0.5, 0.5] scaling, and decode
  through a ConvTranspose tower mirroring the encoder (ref:
  tf/models/components/conv_transpose_atari.py:25) whenever the conv
  stack inverts exactly; otherwise an MLP decoder with a warning.
* Single local env loop — DreamerV3's replay/train ratio makes the model
  updates, not env stepping, the budget.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DreamerV3)
        self.lr = 4e-4
        self.ac_lr = 1e-4
        self.grad_clip = 100.0
        self.gamma = 0.997
        self.lambda_ = 0.95
        self.horizon = 10           # imagination length
        self.batch_size = 8         # replayed sequences per update
        self.batch_length = 16      # steps per replayed sequence
        self.deter_dim = 128
        self.stoch_groups = 8
        self.stoch_classes = 8
        self.hidden = 128
        self.free_bits = 1.0
        self.beta_dyn = 0.5
        self.beta_rep = 0.1
        self.entropy_coeff = 3e-3
        self.critic_ema = 0.98
        self.unimix = 0.01
        #: Twohot symlog distributional reward/value heads (ref:
        #: reward_predictor_layer.py — K buckets spanning symlog
        #: [-20, 20] covers env rewards/returns up to ±400M).
        self.num_buckets = 255
        self.bucket_low = -20.0
        self.bucket_high = 20.0
        #: (H, W, C) to run the conv encoder on PIXEL observations (ref:
        #: the reference's CNN encoder tier; None = vector obs, MLP
        #: encoder).  Pixel decoding mirrors the encoder through a
        #: ConvTranspose tower (ref: conv_transpose_atari.py:25) whenever
        #: the conv stack inverts exactly; an MLP decoder is the fallback.
        self.obs_shape = None
        self.conv_filters = ((16, 4, 2), (32, 3, 1))
        self.env_steps_per_iteration = 200
        self.updates_per_iteration = 20
        self.min_buffer_steps = 300
        self.train_batch_size = 128  # unused; base-config surface


# ------------------------------------------------------------ math utils
def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


# ------------------------------------------------- twohot symlog heads
# (ref: rllib/algorithms/dreamerv3/tf/dreamerv3_tf_learner.py:398-405 —
# reward/value are DISTRIBUTIONS over linearly-spaced symlog-space
# buckets, not scalar regressions: the twohot cross-entropy is
# scale-robust and its gradient does not vanish for rare large returns.)
def _buckets(num: int, lo: float, hi: float):
    return jnp.linspace(lo, hi, num)


def twohot(x, buckets):
    """Twohot encoding of symlog-space targets over `buckets` (K,): the
    probability mass splits linearly between the two nearest buckets."""
    K = buckets.shape[0]
    x = jnp.clip(x, buckets[0], buckets[-1])
    k1 = jnp.clip(jnp.searchsorted(buckets, x), 1, K - 1)
    k0 = k1 - 1
    b0 = buckets[k0]
    b1 = buckets[k1]
    w1 = (x - b0) / jnp.maximum(b1 - b0, 1e-8)
    w0 = 1.0 - w1
    out = (jax.nn.one_hot(k0, K) * w0[..., None]
           + jax.nn.one_hot(k1, K) * w1[..., None])
    return out


def _head_mean(logits, buckets):
    """symexp(E[bucket]) of a twohot head: the expectation is taken in
    SYMLOG space over the linearly-spaced buckets, then inverse-symlog'd —
    exactly the reference's decode (reward_predictor_layer.py computes
    sum(probs * linspace) and dreamer_model.py applies inverse_symlog)."""
    probs = jax.nn.softmax(logits, -1)
    return symexp(jnp.sum(probs * buckets, -1))


def _head_loss(logits, target_raw, buckets):
    """Cross-entropy of twohot(symlog(target)) under the head's logits."""
    tgt = twohot(symlog(target_raw), buckets)
    return -jnp.sum(tgt * jax.nn.log_softmax(logits, -1), -1)


def _mlp_params(key, sizes: List[int]) -> List[Dict[str, Any]]:
    layers = []
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        scale = 1.0 / np.sqrt(sizes[i])
        layers.append({
            "w": jax.random.normal(k, (sizes[i], sizes[i + 1])) * scale,
            "b": jnp.zeros(sizes[i + 1]),
        })
    return layers


def _mlp(params: List[Dict[str, Any]], x, final_act=None):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.silu(x)
        elif final_act is not None:
            x = final_act(x)
    return x


def _zero_final(layers: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Zero the last layer: randomly initialized reward/critic heads emit
    large early predictions that delay learning (Hafner et al. 2023; ref:
    reward_predictor_layer.py kernel_initializer='zeros')."""
    layers[-1]["w"] = jnp.zeros_like(layers[-1]["w"])
    return layers


def _deconv_invertible(obs_shape, conv_filters) -> bool:
    """A VALID conv stack mirrors exactly through conv_transpose only when
    no layer's floor-division drops rows ((in - k) % s == 0 throughout)."""
    h, w, _ = obs_shape
    for _out_c, k, s in conv_filters:
        if (h - k) % s or (w - k) % s:
            return False
        h = (h - k) // s + 1
        w = (w - k) // s + 1
    return True


def _deconv_init(key, obs_shape, conv_filters, init_fn) -> list:
    """Transposed mirror of conv_stack_init: layer i maps encoder layer
    -(i+1)'s output channels back to its input channels (ref:
    conv_transpose_atari.py:25 — the ConvTranspose tower)."""
    chain = [obs_shape[-1]] + [f[0] for f in conv_filters]
    deconvs = []
    for i in range(len(conv_filters) - 1, -1, -1):
        _out_c, k, _s = conv_filters[i]
        key, sub = jax.random.split(key)
        deconvs.append({"w": init_fn(sub, (k, k, chain[i + 1], chain[i])),
                        "b": jnp.zeros((chain[i],), jnp.float32)})
    return deconvs


def _deconv_apply(deconvs, conv_filters, x, act):
    """NHWC VALID conv_transpose stack; final layer linear (predicts pixels
    in the [-0.5, 0.5] preprocessing space)."""
    n = len(deconvs)
    for j, layer in enumerate(deconvs):
        _out_c, k, s = conv_filters[n - 1 - j]
        x = jax.lax.conv_transpose(
            x, layer["w"], strides=(s, s), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + layer["b"]
        if j < n - 1:
            x = act(x)
    return x


def _unimix_logits(logits, unimix: float, classes: int):
    probs = jax.nn.softmax(logits, -1)
    probs = (1 - unimix) * probs + unimix / classes
    return jnp.log(probs)


def _sample_onehot(key, logits):
    """Straight-through one-hot categorical sample (per latent group)."""
    idx = jax.random.categorical(key, logits, axis=-1)
    onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)
    probs = jax.nn.softmax(logits, -1)
    return probs + jax.lax.stop_gradient(onehot - probs)


def _kl_categorical(p_logits, q_logits):
    """KL(p || q) summed over classes and groups, per batch element."""
    p = jax.nn.softmax(p_logits, -1)
    logp = jax.nn.log_softmax(p_logits, -1)
    logq = jax.nn.log_softmax(q_logits, -1)
    return jnp.sum(p * (logp - logq), axis=(-2, -1))


class DreamerV3(Algorithm):
    config_class = DreamerV3Config
    learner_class = None  # self-contained: world model + AC live here

    # ------------------------------------------------------------- setup
    def setup(self, config) -> None:
        cfg = self._coerce_config(config)
        from ray_tpu.rl.utils.metrics import MetricsLogger

        self.algo_config = cfg
        self.metrics = MetricsLogger()
        self._lifetime_steps = 0
        self.env_runner_group = _NullRunnerGroup()

        self._env = self._make_env()
        self._obs_dim = int(np.prod(self._env.observation_space.shape))
        self._n_actions = int(self._env.action_space.n)
        self._pixel = cfg.obs_shape is not None
        env_shape = tuple(self._env.observation_space.shape)
        if self._pixel and tuple(cfg.obs_shape) != env_shape:
            # Compare SHAPES, not element counts: a permuted obs_shape
            # (CHW vs HWC) has the same prod but scrambles every pixel.
            raise ValueError(
                f"obs_shape {tuple(cfg.obs_shape)} does not match the "
                f"env's observation shape {env_shape}")
        self._deconv = self._pixel and _deconv_invertible(cfg.obs_shape,
                                                          cfg.conv_filters)
        if self._pixel and not self._deconv:
            import warnings

            warnings.warn(
                "DreamerV3: conv_filters do not invert exactly on "
                f"obs_shape {tuple(cfg.obs_shape)} ((in-k) % s != 0 at some "
                "layer); pixel decoder falls back to an MLP",
                RuntimeWarning, stacklevel=2)
        self._head_buckets = _buckets(cfg.num_buckets, cfg.bucket_low,
                                      cfg.bucket_high)
        self._rng = np.random.default_rng(cfg.seed)
        self._key = jax.random.key(cfg.seed)
        self._params = self._init_params()
        self._target_critic = jax.tree_util.tree_map(
            lambda x: x, self._params["critic"])
        clip = optax.clip_by_global_norm(cfg.grad_clip)
        self._wm_opt = optax.chain(clip, optax.adam(cfg.lr))
        self._ac_opt = optax.chain(clip, optax.adam(cfg.ac_lr))
        wm, ac = self._split(self._params)
        self._wm_state = self._wm_opt.init(wm)
        self._ac_state = self._ac_opt.init(ac)
        self._retnorm = 1.0  # EMA of the imagined-return 5-95% span
        self._buffer: List[Dict[str, np.ndarray]] = []  # episode segments
        self._buffer_steps = 0
        self._episode_returns: List[float] = []
        self._obs = None
        self._filter_state = None
        self._wm_update = jax.jit(self._make_wm_update())
        self._ac_update = jax.jit(self._make_ac_update())
        self._policy_step = jax.jit(self._make_policy_step())

    def _split(self, params):
        wm = {k: v for k, v in params.items()
              if k not in ("actor", "critic")}
        ac = {"actor": params["actor"], "critic": params["critic"]}
        return wm, ac

    def _init_params(self) -> Dict[str, Any]:
        cfg = self.algo_config
        D, S, C, H = (cfg.deter_dim, cfg.stoch_groups, cfg.stoch_classes,
                      cfg.hidden)
        Z = S * C
        O, A = self._obs_dim, self._n_actions
        k = iter(jax.random.split(jax.random.key(cfg.seed + 1), 14))
        feat = D + Z
        if self._pixel:
            from ray_tpu.rl.core.rl_module import conv_out_dim, conv_stack_init

            def init_kernel(kk, shape):
                scale = 1.0 / np.sqrt(shape[0] * shape[1] * shape[2])
                return jax.random.normal(kk, shape) * scale

            convs = conv_stack_init(next(k), cfg.obs_shape,
                                    cfg.conv_filters, init_kernel)
            ch, cw, cc = conv_out_dim(cfg.obs_shape, cfg.conv_filters)
            encoder: Any = {"convs": convs,
                            "torso": _mlp_params(next(k),
                                                 [ch * cw * cc, H])}
            if self._deconv:
                decoder: Any = {
                    "torso": _mlp_params(next(k), [feat, ch * cw * cc]),
                    "deconvs": _deconv_init(next(k), cfg.obs_shape,
                                            cfg.conv_filters, init_kernel),
                }
            else:
                decoder = _mlp_params(next(k), [feat, H, O])
        else:
            encoder = _mlp_params(next(k), [O, H, H])
            decoder = _mlp_params(next(k), [feat, H, O])
        K = cfg.num_buckets
        return {
            "encoder": encoder,
            "gru_in": _mlp_params(next(k), [Z + A, D]),
            # GRU weights: update/reset/candidate over [input, state].
            "gru": {"w": jax.random.normal(next(k), (2 * D, 3 * D)) * 0.02,
                    "b": jnp.zeros(3 * D)},
            "prior": _mlp_params(next(k), [D, H, Z]),
            "post": _mlp_params(next(k), [D + H, H, Z]),
            "decoder": decoder,
            "reward": _zero_final(_mlp_params(next(k), [feat, H, K])),
            "cont": _mlp_params(next(k), [feat, H, 1]),
            "actor": _mlp_params(next(k), [feat, H, A]),
            "critic": _zero_final(_mlp_params(next(k), [feat, H, K])),
        }

    # --------------------------------------------------------- RSSM core
    def _preprocess(self, obs):
        """Observation normalization: pixels to [-0.5, 0.5] (the DreamerV3
        convention), vectors through symlog.  The decoder reconstructs
        THIS space."""
        if self._pixel:
            return obs / 255.0 - 0.5
        return symlog(obs)

    def _encode(self, params, obs_pre):
        enc = params["encoder"]
        if not self._pixel:
            return _mlp(enc, obs_pre)
        from ray_tpu.rl.core.rl_module import conv_stack_apply

        cfg = self.algo_config
        lead = obs_pre.shape[:-1]
        x = obs_pre.reshape((-1, *cfg.obs_shape))
        x = conv_stack_apply(enc["convs"], cfg.conv_filters, x, jax.nn.silu)
        x = _mlp(enc["torso"], x, final_act=jax.nn.silu)
        return x.reshape((*lead, x.shape[-1]))

    def _decode(self, params, feat):
        """feat (..., F) -> reconstruction in preprocessing space, flat
        (..., O).  Pixels run the ConvTranspose mirror of the encoder when
        it inverts exactly; everything else the MLP decoder."""
        dec = params["decoder"]
        if not self._deconv:
            return _mlp(dec, feat)
        from ray_tpu.rl.core.rl_module import conv_out_dim

        cfg = self.algo_config
        ch, cw, cc = conv_out_dim(cfg.obs_shape, cfg.conv_filters)
        lead = feat.shape[:-1]
        x = _mlp(dec["torso"], feat.reshape((-1, feat.shape[-1])),
                 final_act=jax.nn.silu)
        x = x.reshape((-1, ch, cw, cc))
        x = _deconv_apply(dec["deconvs"], cfg.conv_filters, x, jax.nn.silu)
        return x.reshape((*lead, self._obs_dim))

    def _gru(self, params, x, h):
        gates = jnp.concatenate([x, h], -1) @ params["gru"]["w"] \
            + params["gru"]["b"]
        u, r, c = jnp.split(gates, 3, -1)
        u = jax.nn.sigmoid(u)
        r = jax.nn.sigmoid(r)
        cand = jnp.tanh(r * c)
        return u * cand + (1 - u) * h

    def _prior_logits(self, params, h):
        cfg = self.algo_config
        logits = _mlp(params["prior"], h)
        logits = logits.reshape(*h.shape[:-1], cfg.stoch_groups,
                                cfg.stoch_classes)
        return _unimix_logits(logits, cfg.unimix, cfg.stoch_classes)

    def _post_logits(self, params, h, embed):
        cfg = self.algo_config
        logits = _mlp(params["post"], jnp.concatenate([h, embed], -1))
        logits = logits.reshape(*h.shape[:-1], cfg.stoch_groups,
                                cfg.stoch_classes)
        return _unimix_logits(logits, cfg.unimix, cfg.stoch_classes)

    def _step_deter(self, params, h, z_flat, action_onehot):
        x = _mlp(params["gru_in"], jnp.concatenate([z_flat, action_onehot],
                                                   -1))
        return self._gru(params, x, h)

    # ----------------------------------------------------- world-model loss
    def _make_wm_update(self):
        cfg = self.algo_config

        def loss_fn(wm_params, batch, key):
            obs = self._preprocess(batch["obs"])    # (B, T, O)
            acts = batch["actions"]                 # (B, T) int32
            B, T = acts.shape
            embed = self._encode(wm_params, obs)
            a_onehot = jax.nn.one_hot(acts, self._n_actions)
            keys = jax.random.split(key, T)

            def step(carry, t_in):
                h, z_flat = carry
                a_prev, e_t, k_t, first = t_in
                # is_first masking (the reference's boundary handling):
                # sequences pack ACROSS episode resets, so the recurrent
                # state and previous action zero out at each episode start
                # — no transition is ever learned across a reset.
                keep = (1.0 - first)[:, None]
                h = h * keep
                z_flat = z_flat * keep
                a_prev = a_prev * keep
                h = self._step_deter(wm_params, h, z_flat, a_prev)
                prior = self._prior_logits(wm_params, h)
                post = self._post_logits(wm_params, h, e_t)
                z = _sample_onehot(k_t, post)
                z_flat = z.reshape(B, -1)
                return (h, z_flat), (h, z_flat, prior, post)

            h0 = jnp.zeros((B, cfg.deter_dim))
            z0 = jnp.zeros((B, cfg.stoch_groups * cfg.stoch_classes))
            # Inputs are time-major for the scan: a_prev[t] = action taken
            # BEFORE observing obs[t] (shifted; first step gets zeros).
            a_prev = jnp.concatenate(
                [jnp.zeros((1, B, self._n_actions)),
                 jnp.transpose(a_onehot, (1, 0, 2))[:-1]], 0)
            e_tm = jnp.transpose(embed, (1, 0, 2))
            firsts = jnp.transpose(batch["is_first"], (1, 0))
            (_, _), (hs, zs, priors, posts) = jax.lax.scan(
                step, (h0, z0), (a_prev, e_tm, keys, firsts))
            feat = jnp.concatenate([hs, zs], -1)    # (T, B, feat)

            recon = self._decode(wm_params, feat)
            rew_logits = _mlp(wm_params["reward"], feat)   # (T, B, K)
            cont_logit = _mlp(wm_params["cont"], feat)[..., 0]
            obs_tm = jnp.transpose(obs, (1, 0, 2))
            rew_tm = jnp.transpose(batch["rewards"], (1, 0))
            cont_tm = jnp.transpose(1.0 - batch["terminateds"], (1, 0))

            recon_loss = jnp.mean(jnp.sum((recon - obs_tm) ** 2, -1))
            reward_loss = jnp.mean(
                _head_loss(rew_logits, rew_tm, self._head_buckets))
            cont_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(cont_logit, cont_tm))
            dyn = _kl_categorical(jax.lax.stop_gradient(posts), priors)
            rep = _kl_categorical(posts, jax.lax.stop_gradient(priors))
            kl = (cfg.beta_dyn * jnp.maximum(dyn, cfg.free_bits)
                  + cfg.beta_rep * jnp.maximum(rep, cfg.free_bits))
            total = recon_loss + reward_loss + cont_loss + jnp.mean(kl)
            aux = {"recon_loss": recon_loss, "reward_loss": reward_loss,
                   "cont_loss": cont_loss, "kl": jnp.mean(dyn),
                   "feat": jax.lax.stop_gradient(feat)}
            return total, aux

        def update(wm_params, opt_state, batch, key):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(wm_params, batch, key)
            updates, opt_state = self._wm_opt.update(grads, opt_state,
                                                     wm_params)
            wm_params = optax.apply_updates(wm_params, updates)
            return wm_params, opt_state, loss, aux

        return update

    # ----------------------------------------------------- actor-critic loss
    def _make_ac_update(self):
        cfg = self.algo_config

        def imagine(wm_params, actor_params, feat0, key):
            """Roll the prior forward H steps with actor actions."""
            B = feat0.shape[0]
            h = feat0[:, :cfg.deter_dim]
            z_flat = feat0[:, cfg.deter_dim:]
            keys = jax.random.split(key, cfg.horizon)

            def step(carry, k_t):
                h, z_flat = carry
                feat = jnp.concatenate([h, z_flat], -1)
                ka, kz = jax.random.split(k_t)
                logits = _mlp(actor_params, feat)
                act = jax.random.categorical(ka, logits)
                a_onehot = jax.nn.one_hot(act, self._n_actions)
                h = self._step_deter(wm_params, h, z_flat, a_onehot)
                prior = self._prior_logits(wm_params, h)
                z = _sample_onehot(kz, prior)
                z_flat = z.reshape(B, -1)
                return (h, z_flat), (feat, act)

            (_, _), (feats, acts) = jax.lax.scan(step, (h, z_flat), keys)
            return feats, acts  # (H, B, feat), (H, B)

        def loss_fn(ac_params, wm_params, target_critic, feat0, key,
                    retnorm):
            feats, acts = imagine(wm_params, ac_params["actor"], feat0, key)
            bk = self._head_buckets
            rew = _head_mean(_mlp(wm_params["reward"], feats), bk)
            cont = jax.nn.sigmoid(_mlp(wm_params["cont"], feats)[..., 0])
            disc = cfg.gamma * cont
            v_target = _head_mean(_mlp(target_critic, feats), bk)

            def lam_step(nxt, t_in):
                r_t, d_t, v_next = t_in
                ret = r_t + d_t * ((1 - cfg.lambda_) * v_next
                                   + cfg.lambda_ * nxt)
                return ret, ret

            v_next = jnp.concatenate([v_target[1:], v_target[-1:]], 0)
            _, returns = jax.lax.scan(
                lam_step, v_target[-1],
                (rew, disc, v_next), reverse=True)
            returns = jax.lax.stop_gradient(returns)      # (H, B)

            v_logits = _mlp(ac_params["critic"], feats)
            critic_loss = jnp.mean(_head_loss(v_logits, returns, bk))
            v_mean = _head_mean(jax.lax.stop_gradient(v_logits), bk)

            logits = _mlp(ac_params["actor"], feats)
            logp = jax.nn.log_softmax(logits, -1)
            act_logp = jnp.take_along_axis(
                logp, acts[..., None], -1)[..., 0]
            adv = (returns - v_mean) / retnorm
            # Trajectory discount weights so late imagined steps (past
            # predicted termination) contribute less.
            weights = jax.lax.stop_gradient(jnp.cumprod(
                jnp.concatenate([jnp.ones_like(disc[:1]), disc[:-1]], 0), 0))
            entropy = -jnp.sum(jnp.exp(logp) * logp, -1)
            actor_loss = -jnp.mean(
                weights * (act_logp * jax.lax.stop_gradient(adv)
                           + cfg.entropy_coeff * entropy))
            total = actor_loss + critic_loss
            span = jnp.percentile(returns, 95) - jnp.percentile(returns, 5)
            aux = {"actor_loss": actor_loss, "critic_loss": critic_loss,
                   "imagined_return": jnp.mean(returns),
                   "return_span": span,
                   "actor_entropy": jnp.mean(entropy)}
            return total, aux

        def update(ac_params, opt_state, wm_params, target_critic, feat0,
                   key, retnorm):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(ac_params, wm_params, target_critic,
                                       feat0, key, retnorm)
            updates, opt_state = self._ac_opt.update(grads, opt_state,
                                                     ac_params)
            ac_params = optax.apply_updates(ac_params, updates)
            new_target = jax.tree_util.tree_map(
                lambda t, o: cfg.critic_ema * t + (1 - cfg.critic_ema) * o,
                target_critic, ac_params["critic"])
            return ac_params, opt_state, new_target, loss, aux

        return update

    # ------------------------------------------------------------- acting
    def _make_policy_step(self):
        cfg = self.algo_config

        def step(params, h, z_flat, a_prev_onehot, obs, key, explore):
            embed = self._encode(params, self._preprocess(obs))
            h = self._step_deter(params, h, z_flat, a_prev_onehot)
            post = self._post_logits(params, h, embed)
            kz, ka = jax.random.split(key)
            z = _sample_onehot(kz, post)
            z_flat = z.reshape(z.shape[0], -1)
            feat = jnp.concatenate([h, z_flat], -1)
            logits = _mlp(params["actor"], feat)
            act = jnp.where(explore,
                            jax.random.categorical(ka, logits),
                            jnp.argmax(logits, -1))
            return h, z_flat, act

        return step

    def _act(self, obs: np.ndarray, explore: bool = True) -> int:
        cfg = self.algo_config
        if self._filter_state is None:
            self._filter_state = (
                jnp.zeros((1, cfg.deter_dim)),
                jnp.zeros((1, cfg.stoch_groups * cfg.stoch_classes)),
                jnp.zeros((1, self._n_actions)))
        h, z_flat, a_prev = self._filter_state
        self._key, k = jax.random.split(self._key)
        h, z_flat, act = self._policy_step(
            self._params, h, z_flat, a_prev,
            jnp.asarray(obs, jnp.float32)[None], k, jnp.asarray(explore))
        action = int(act[0])
        self._filter_state = (h, z_flat,
                              jax.nn.one_hot(act, self._n_actions))
        return action

    # ------------------------------------------------------- replay buffer
    def _collect(self, n_steps: int) -> int:
        env = self._env
        seg: Dict[str, list] = {"obs": [], "actions": [], "rewards": [],
                                "terminateds": [], "is_first": []}
        collected = 0
        if self._obs is None:
            self._obs, _ = env.reset(seed=int(self._rng.integers(1 << 30)))
            self._filter_state = None
            self._ep_return = 0.0
            self._ep_first = True
        while collected < n_steps:
            obs = np.asarray(self._obs, np.float32).ravel()
            act = self._act(obs)
            nxt, rew, term, trunc, _ = env.step(act)
            seg["obs"].append(obs)
            seg["actions"].append(act)
            seg["rewards"].append(float(rew))
            seg["terminateds"].append(1.0 if term else 0.0)
            seg["is_first"].append(1.0 if self._ep_first else 0.0)
            self._ep_first = False
            self._ep_return += float(rew)
            collected += 1
            if term or trunc:
                self._episode_returns.append(self._ep_return)
                self._obs, _ = env.reset(
                    seed=int(self._rng.integers(1 << 30)))
                self._filter_state = None
                self._ep_return = 0.0
                self._ep_first = True
            else:
                self._obs = nxt
        segment = {k: np.asarray(v, np.float32 if k != "actions"
                                 else np.int32) for k, v in seg.items()}
        self._buffer.append(segment)
        self._buffer_steps += collected
        # Bounded replay: drop oldest segments past ~50k steps.
        while self._buffer_steps > 50_000 and len(self._buffer) > 1:
            self._buffer_steps -= len(self._buffer[0]["actions"])
            self._buffer.pop(0)
        return collected

    def _sample_sequences(self) -> Optional[Dict[str, jnp.ndarray]]:
        cfg = self.algo_config
        B, L = cfg.batch_size, cfg.batch_length
        eligible = [s for s in self._buffer if len(s["actions"]) >= L]
        if not eligible:
            return None
        batch: Dict[str, list] = {k: [] for k in
                                  ("obs", "actions", "rewards",
                                   "terminateds", "is_first")}
        for _ in range(B):
            seg = eligible[self._rng.integers(len(eligible))]
            start = self._rng.integers(0, len(seg["actions"]) - L + 1)
            for k in batch:
                batch[k].append(seg[k][start:start + L])
        return {k: jnp.asarray(np.stack(v)) for k, v in batch.items()}

    # ------------------------------------------------------- training step
    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        stepped = self._collect(cfg.env_steps_per_iteration)
        self._lifetime_steps += stepped
        if self._buffer_steps < cfg.min_buffer_steps:
            return {"learners": {}, "buffer_steps": self._buffer_steps}

        wm, ac = self._split(self._params)
        results: Dict[str, Any] = {}
        for _ in range(cfg.updates_per_iteration):
            batch = self._sample_sequences()
            if batch is None:
                break
            self._key, k1, k2 = jax.random.split(self._key, 3)
            wm, self._wm_state, wm_loss, wm_aux = self._wm_update(
                wm, self._wm_state, batch, k1)
            feat = wm_aux["feat"]
            feat0 = feat.reshape(-1, feat.shape[-1])
            ac, self._ac_state, self._target_critic, ac_loss, ac_aux = \
                self._ac_update(ac, self._ac_state, wm,
                                self._target_critic, feat0, k2,
                                jnp.float32(max(self._retnorm, 1.0)))
            self._retnorm = 0.99 * self._retnorm \
                + 0.01 * float(ac_aux["return_span"])
            results = {"world_model_loss": float(wm_loss),
                       "recon_loss": float(wm_aux["recon_loss"]),
                       "reward_loss": float(wm_aux["reward_loss"]),
                       "kl": float(wm_aux["kl"]),
                       "actor_loss": float(ac_aux["actor_loss"]),
                       "critic_loss": float(ac_aux["critic_loss"]),
                       "imagined_return": float(ac_aux["imagined_return"]),
                       "actor_entropy": float(ac_aux["actor_entropy"])}
        self._params = {**wm, **ac}
        out = {"learners": results, "buffer_steps": self._buffer_steps}
        if self._episode_returns:
            recent = self._episode_returns[-20:]
            out["episode_return_mean"] = float(np.mean(recent))
        return out

    def _make_env(self):
        env = self.algo_config.env
        return env() if callable(env) else __import__(
            "gymnasium").make(env)

    # --------------------------------------------------------- evaluation
    def evaluate(self) -> Dict[str, Any]:
        """Greedy-policy episodes on a fresh env (the base Algorithm's
        evaluate needs the learner-group machinery DreamerV3 replaces)."""
        env = self._make_env()
        returns = []
        n_episodes = int(getattr(self.algo_config,
                                 "evaluation_duration", 5) or 5)
        for ep in range(n_episodes):
            obs, _ = env.reset(seed=1000 + ep)
            saved = self._filter_state
            self._filter_state = None
            total, done = 0.0, False
            while not done:
                act = self._act(np.asarray(obs, np.float32).ravel(),
                                explore=False)
                obs, rew, term, trunc, _ = env.step(act)
                total += float(rew)
                done = term or trunc
            self._filter_state = saved
            returns.append(total)
        try:
            env.close()
        except Exception:
            pass
        return {"env_runners": {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": len(returns)}}

    # ------------------------------------------------------- checkpointing
    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        import os
        import pickle

        state = {
            "params": jax.device_get(self._params),
            "target_critic": jax.device_get(self._target_critic),
            "wm_state": jax.device_get(self._wm_state),
            "ac_state": jax.device_get(self._ac_state),
            "retnorm": self._retnorm,
            "lifetime_steps": self._lifetime_steps,
        }
        with open(os.path.join(checkpoint_dir, "dreamer_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)
        return None

    def load_checkpoint(self, data, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "dreamer_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self._params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self._target_critic = jax.tree_util.tree_map(
            jnp.asarray, state["target_critic"])
        self._wm_state = jax.tree_util.tree_map(jnp.asarray,
                                                state["wm_state"])
        self._ac_state = jax.tree_util.tree_map(jnp.asarray,
                                                state["ac_state"])
        self._retnorm = state["retnorm"]
        self._lifetime_steps = state["lifetime_steps"]
        self._filter_state = None

    def cleanup(self) -> None:
        try:
            self._env.close()
        except Exception:
            pass


class _NullRunnerGroup:
    """Algorithm.step() surface for a self-contained env loop."""

    def get_metrics(self) -> List[Dict[str, Any]]:
        return []

    def stop(self) -> None:
        pass

    def sync_weights(self, *a, **kw) -> None:
        pass
