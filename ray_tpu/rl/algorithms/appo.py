"""APPO — asynchronous PPO: IMPALA's actor-learner architecture with the
PPO clipped surrogate over V-trace-corrected advantages.

(ref: rllib/algorithms/appo/appo.py APPOConfig/APPO — 'asynchronous variant
of PPO based on the IMPALA architecture'; loss in
rllib/algorithms/appo/torch/appo_torch_learner.py — clipped surrogate with
importance ratios against the behavior policy, V-trace value targets,
periodic target-network refresh.)

Inherits IMPALA's async sampling loop, fragment batching, and V-trace
machinery wholesale; only the loss differs.  The target-network refresh is
modeled by the broadcast_interval weight sync (the behavior policy IS the
last-broadcast snapshot, which is what the ratio clips against).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp

from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig, IMPALALearner
from ray_tpu.rl.core.rl_module import Columns


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param = 0.4  # ref: appo.py default clip
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        self.kl_target = 0.01


class APPOLearner(IMPALALearner):
    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        cfg = self.config
        (dist, inputs, target_logp, values, mask, denom, vs, pg_adv) = \
            self._vtrace_terms(params, batch)

        # PPO clipped surrogate with ratios against the BEHAVIOR policy
        # (the last broadcast snapshot) — ref: appo_torch_learner.py.
        ratio = jnp.exp(target_logp - batch[Columns.ACTION_LOGP])
        surrogate = jnp.minimum(
            pg_adv * ratio,
            pg_adv * jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param))
        policy_loss = -jnp.sum(surrogate * mask) / denom
        value_loss = 0.5 * jnp.sum(jnp.square(values - vs) * mask) / denom
        entropy = jnp.sum(dist.entropy(inputs) * mask) / denom
        total = (policy_loss + cfg.vf_loss_coeff * value_loss
                 - cfg.entropy_coeff * entropy)
        metrics = {"policy_loss": policy_loss, "vf_loss": value_loss,
                   "entropy": entropy,
                   "mean_ratio": jnp.sum(ratio * mask) / denom}
        if cfg.use_kl_loss:
            kl = jnp.sum((batch[Columns.ACTION_LOGP] - target_logp) * mask) / denom
            # ADAPTIVE coefficient rides the batch as a 0-d array (no
            # recompile); APPO._augment_batch injects + _after_learn adapts
            # toward kl_target (ref: appo.py after_train_step).
            kl_coeff = batch.get("kl_coeff", jnp.float32(cfg.kl_coeff))
            total = total + kl_coeff * jnp.maximum(kl, 0.0)
            metrics["mean_kl"] = kl
        return total, metrics


class APPO(IMPALA):
    learner_class = APPOLearner
    config_class = APPOConfig

    def _augment_batch(self, batch):
        cfg = self.algo_config
        if cfg.use_kl_loss:
            if not hasattr(self, "_kl_coeff"):
                self._kl_coeff = float(cfg.kl_coeff)
            import numpy as np

            batch["kl_coeff"] = np.float32(self._kl_coeff)
        return batch

    def _after_learn(self, results) -> None:
        """Adaptive KL schedule toward kl_target (ref: appo.py / this
        repo's PPO: 1.5x when overshooting 2x target, halve under 0.5x)."""
        cfg = self.algo_config
        kl = results.get("mean_kl")
        if not cfg.use_kl_loss or kl is None:
            return
        if kl > 2.0 * cfg.kl_target:
            self._kl_coeff *= 1.5
        elif kl < 0.5 * cfg.kl_target:
            self._kl_coeff *= 0.5
        results["curr_kl_coeff"] = self._kl_coeff
