"""BC — behavior cloning from offline data.

(ref: rllib/algorithms/bc/bc.py BCConfig/BC; loss in
rllib/algorithms/bc/torch/bc_torch_learner.py — negative log-likelihood of
the dataset actions under the policy.)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax.numpy as jnp

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.rl_module import Columns
from ray_tpu.rl.offline import OfflineData


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_epochs = 1
        self.minibatch_size = None
        self.entropy_coeff = 0.0
        # offline input
        self.input_: Union[str, Dict, None] = None
        self.input_format = "parquet"
        self.updates_per_iteration = 20

    def offline_data(self, *, input_=None, input_format: Optional[str] = None,
                     updates_per_iteration: Optional[int] = None
                     ) -> "BCConfig":
        """(ref: AlgorithmConfig.offline_data(input_=...))"""
        if input_ is not None:
            self.input_ = input_
        if input_format is not None:
            self.input_format = input_format
        if updates_per_iteration is not None:
            self.updates_per_iteration = updates_per_iteration
        return self


class BCLearner(JaxLearner):
    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        out = self.module.forward_train(params, batch[Columns.OBS])
        inputs = out[Columns.ACTION_DIST_INPUTS]
        dist = self.module.action_dist
        logp = dist.logp(inputs, batch[Columns.ACTIONS])
        loss = -jnp.mean(logp)
        coeff = getattr(self.config, "entropy_coeff", 0.0)
        entropy = jnp.mean(dist.entropy(inputs))
        if coeff:
            loss = loss - coeff * entropy
        return loss, {"bc_logp": jnp.mean(logp), "entropy": entropy}


class BC(Algorithm):
    """Offline: no env sampling; each iteration runs K learner updates over
    dataset minibatches, syncing weights for evaluation."""

    learner_class = BCLearner
    config_class = BCConfig

    def setup(self, config) -> None:
        super().setup(config)
        cfg = self.algo_config
        assert cfg.input_ is not None, \
            "offline algorithms need .offline_data(input_=...)"
        self.offline = OfflineData(cfg.input_, format=cfg.input_format,
                                   seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        results: Dict[str, Any] = {}
        for _ in range(max(1, cfg.updates_per_iteration)):
            batch = self.offline.sample(cfg.train_batch_size)
            results = self.learner_group.update_from_batch(
                batch, num_epochs=cfg.num_epochs,
                minibatch_size=cfg.minibatch_size)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {"learners": results, "dataset_size": self.offline.size}
