"""DQN — double-Q with target network and (prioritized) replay.

(ref: rllib/algorithms/dqn/dqn.py DQNConfig/DQN; loss in
rllib/algorithms/dqn/torch/dqn_torch_learner.py — double-Q TD target,
Huber loss; target net sync every target_network_update_freq steps.)
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.connectors import episodes_to_transitions
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.rl_module import Columns, DefaultQModule


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.module_class = DefaultQModule
        self.lr = 5e-4
        self.train_batch_size = 32
        self.num_epochs = 1
        self.minibatch_size = None
        self.rollout_fragment_length = 4
        self.replay_buffer_capacity = 50_000
        self.prioritized_replay = False
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500  # in learner update steps
        self.n_step = 1
        self.double_q = True
        self.epsilon = [(0, 1.0), (10000, 0.05)]  # piecewise-linear schedule
        self.tau = 1.0  # 1.0 = hard target sync


class DQNLearner(JaxLearner):
    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        cfg = self.config
        q_all = self.module.forward_train(params, batch[Columns.OBS])["q_values"]
        q_taken = jnp.take_along_axis(
            q_all, batch[Columns.ACTIONS][..., None].astype(jnp.int32), axis=-1
        )[..., 0]

        q_next_target = self.module.forward_target(params, batch[Columns.NEXT_OBS])
        if cfg.double_q:
            # Online net picks the argmax; target net evaluates it.
            q_next_online = self.module.forward_train(
                params, batch[Columns.NEXT_OBS])["q_values"]
            best = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(q_next_target, best[..., None], axis=-1)[..., 0]
        else:
            q_next = jnp.max(q_next_target, axis=-1)
        q_next = jax.lax.stop_gradient(q_next)
        target = (batch[Columns.REWARDS]
                  + (cfg.gamma ** cfg.n_step) * (1.0 - batch[Columns.TERMINATEDS])
                  * q_next)
        # The target net must not receive gradients through its pytree copy.
        td = q_taken - jax.lax.stop_gradient(target)
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2, jnp.abs(td) - 0.5)
        weights = batch.get(Columns.WEIGHTS)
        loss = jnp.mean(huber * weights) if weights is not None else jnp.mean(huber)
        return loss, {"td_error_mean": jnp.mean(jnp.abs(td)),
                      "q_mean": jnp.mean(q_taken)}

    def compute_td_errors(self, batch: Dict[str, Any]) -> Any:
        """Per-sample |TD| for prioritized-replay updates (ref: PER priority
        refresh after each train batch)."""
        if not hasattr(self, "_td_fn"):
            cfg = self.config

            def td(params, batch):
                q_all = self.module.forward_train(params,
                                                  batch[Columns.OBS])["q_values"]
                q_taken = jnp.take_along_axis(
                    q_all, batch[Columns.ACTIONS][..., None].astype(jnp.int32),
                    axis=-1)[..., 0]
                q_next_t = self.module.forward_target(params,
                                                      batch[Columns.NEXT_OBS])
                if cfg.double_q:
                    q_next_o = self.module.forward_train(
                        params, batch[Columns.NEXT_OBS])["q_values"]
                    best = jnp.argmax(q_next_o, axis=-1)
                    q_next = jnp.take_along_axis(q_next_t, best[..., None],
                                                 axis=-1)[..., 0]
                else:
                    q_next = jnp.max(q_next_t, axis=-1)
                target = (batch[Columns.REWARDS]
                          + (cfg.gamma ** cfg.n_step)
                          * (1.0 - batch[Columns.TERMINATEDS]) * q_next)
                return jnp.abs(q_taken - target)

            self._td_fn = jax.jit(td)
        batch = {k: v for k, v in batch.items() if k != Columns.WEIGHTS}
        return np.asarray(self._td_fn(self.params, batch))

    def after_update(self, metrics: Dict[str, Any]) -> None:
        cfg = self.config
        if self._steps % max(1, cfg.target_network_update_freq) == 0:
            tau = cfg.tau
            self.params = dict(self.params)
            if tau >= 1.0:
                self.params["target_q"] = jax.tree.map(jnp.copy, self.params["q"])
            else:
                self.params["target_q"] = jax.tree.map(
                    lambda t, o: (1 - tau) * t + tau * o,
                    self.params["target_q"], self.params["q"])


class DQN(Algorithm):
    learner_class = DQNLearner
    config_class = DQNConfig

    def setup(self, config) -> None:
        super().setup(config)
        cfg = self.algo_config
        from ray_tpu.rl.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                     ReplayBuffer)

        self.replay = (PrioritizedReplayBuffer(cfg.replay_buffer_capacity,
                                               seed=cfg.seed)
                       if cfg.prioritized_replay
                       else ReplayBuffer(cfg.replay_buffer_capacity,
                                         seed=cfg.seed))

    def _epsilon(self) -> float:
        """Piecewise-linear interpolation across ALL schedule breakpoints."""
        sched = self.algo_config.epsilon
        t = self._lifetime_steps
        if t <= sched[0][0]:
            return sched[0][1]
        for (t0, e0), (t1, e1) in zip(sched, sched[1:]):
            if t <= t1:
                return e0 + (e1 - e0) * (t - t0) / max(1, t1 - t0)
        return sched[-1][1]

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        warmup = (self._lifetime_steps
                  < cfg.num_steps_sampled_before_learning_starts)
        # Epsilon-greedy: with prob eps sample random actions for the whole
        # fragment (fragments are short — 4 steps default); the other arm is
        # GREEDY argmax over Q (explore=False), not Boltzmann sampling.
        explore_random = warmup or (np.random.random() < self._epsilon())
        episodes = self.env_runner_group.sample(
            num_timesteps=max(cfg.rollout_fragment_length,
                              cfg.train_batch_size if warmup else 0)
            or cfg.rollout_fragment_length,
            random_actions=explore_random, explore=False)
        self._lifetime_steps += sum(len(ep) for ep in episodes)
        self.replay.add(episodes_to_transitions(episodes))
        if warmup or len(self.replay) < cfg.train_batch_size:
            return {"learners": {}, "epsilon": self._epsilon()}
        batch = self.replay.sample(cfg.train_batch_size)
        learner_results = self.learner_group.update_from_batch(batch)
        if cfg.prioritized_replay:
            td = self.learner_group.foreach_learner(
                "compute_td_errors", batch)[0]
            self.replay.update_priorities(td)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {"learners": learner_results, "epsilon": self._epsilon(),
                "replay_size": len(self.replay)}
