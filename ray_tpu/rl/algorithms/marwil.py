"""MARWIL — Monotonic Advantage Re-Weighted Imitation Learning.

(ref: rllib/algorithms/marwil/marwil.py MARWILConfig/MARWIL; loss in
rllib/algorithms/marwil/torch/marwil_torch_learner.py — behavior cloning
re-weighted by exp(beta * advantage), advantage = return-to-go - V(s),
normalized by a running second moment; Wang et al. 2018.)

Shares the offline substrate with BC (OfflineData over flat transition
rows); the returns-to-go column is derived once at setup from the
dataset's reward/terminated columns (row order is episode order — see
offline.record_episodes).  ``beta=0`` recovers plain BC plus a value
baseline, exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithms.bc import BC, BCConfig
from ray_tpu.rl.core.learner import JaxLearner
from ray_tpu.rl.core.rl_module import Columns


class MARWILConfig(BCConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        #: Advantage re-weighting temperature (0 = BC + value baseline).
        self.beta = 1.0
        self.vf_coeff = 1.0
        #: Exponent clip keeping exp(beta * adv_norm) finite early in
        #: training (the reference bounds via its moving-average norm).
        self.max_advantage_exponent = 10.0


class MARWILLearner(JaxLearner):
    def compute_loss(self, params, batch: Dict[str, Any], key) -> Tuple[Any, Dict]:
        cfg = self.config
        out = self.module.forward_train(params, batch[Columns.OBS])
        dist = self.module.action_dist
        inputs = out[Columns.ACTION_DIST_INPUTS]
        logp = dist.logp(inputs, batch[Columns.ACTIONS])
        values = out[Columns.VF_PREDS]
        returns = batch["returns"]
        adv = returns - values
        # Batch second-moment normalizer (the reference keeps a moving
        # average; a per-batch one is the stationary-offline equivalent).
        norm = jnp.sqrt(jnp.mean(jnp.square(jax.lax.stop_gradient(adv))) + 1e-8)
        exponent = jnp.clip(cfg.beta * jax.lax.stop_gradient(adv) / norm,
                            -cfg.max_advantage_exponent,
                            cfg.max_advantage_exponent)
        weights = jnp.exp(exponent)
        policy_loss = -jnp.mean(weights * logp)
        vf_loss = jnp.mean(jnp.square(adv))
        entropy = jnp.mean(dist.entropy(inputs))
        total = policy_loss + cfg.vf_coeff * vf_loss
        coeff = getattr(cfg, "entropy_coeff", 0.0)
        if coeff:
            total = total - coeff * entropy
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_advantage": jnp.mean(adv),
                       "bc_logp": jnp.mean(logp), "entropy": entropy}


def returns_to_go(rewards: np.ndarray, boundaries: np.ndarray,
                  gamma: float) -> np.ndarray:
    """Discounted return from each step to its episode's end, computed over
    flat transition rows in episode order.  ``boundaries`` marks the LAST
    step of each episode (terminated OR truncated — returns must not bleed
    across a time-limit cut); the dataset tail counts as a boundary."""
    out = np.zeros(len(rewards), np.float32)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        if boundaries[i]:
            acc = 0.0
        acc = float(rewards[i]) + gamma * acc
        out[i] = acc
    return out


class MARWIL(BC):
    learner_class = MARWILLearner
    config_class = MARWILConfig

    def setup(self, config) -> None:
        super().setup(config)
        if getattr(self.offline, "is_streaming", False):
            # Whole-dataset returns-to-go needs every episode in memory; a
            # streaming window can't provide that.  Precomputed returns
            # stream through sample() fine.
            if not self.offline.has_column("returns"):
                raise ValueError(
                    "MARWIL on a streaming OfflineData needs a precomputed "
                    "'returns' column (returns-to-go derivation requires the "
                    "full dataset in memory; use streaming=False)")
            return
        cols = self.offline.columns
        if "returns" not in cols:
            if Columns.REWARDS not in cols:
                raise ValueError(
                    "MARWIL needs a 'returns' or 'rewards' column in the "
                    "offline dataset")
            if Columns.TERMINATEDS not in cols:
                # Without boundary flags returns-to-go would treat the
                # whole dataset as ONE episode — silently wrong advantages.
                raise ValueError(
                    "MARWIL needs episode boundaries to derive returns: "
                    "provide a 'returns' column, or record the dataset "
                    "with terminateds/truncateds (offline.record_episodes "
                    "emits both)")
            n = self.offline.size
            term = np.asarray(cols[Columns.TERMINATEDS])
            if Columns.TRUNCATEDS in cols:
                trunc = np.asarray(cols[Columns.TRUNCATEDS])
            else:
                import warnings

                warnings.warn(
                    "offline dataset has no truncateds column (recorded "
                    "before truncation tracking): returns-to-go will bleed "
                    "across time-limit episode cuts", RuntimeWarning,
                    stacklevel=2)
                trunc = np.zeros(n)
            cols["returns"] = returns_to_go(
                np.asarray(cols[Columns.REWARDS], np.float32),
                (term > 0) | (trunc > 0), self.algo_config.gamma)
