"""CQL — conservative Q-learning (offline SAC variant).

(ref: rllib/algorithms/cql/cql.py CQLConfig/CQL; loss in
rllib/algorithms/cql/torch/cql_torch_learner.py — SAC losses plus the
CQL(H) regularizer: logsumexp of Q over random + policy actions minus the
dataset Q, weighted by min_q_weight.)

Built on SACLearner's jitted update via the ``critic_penalty`` hook, so the
conservative term compiles into the same single update step.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rl.algorithms.sac import SAC, SACConfig, SACLearner
from ray_tpu.rl.core.rl_module import Columns
from ray_tpu.rl.offline import OfflineData


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.min_q_weight = 5.0
        self.num_penalty_actions = 4  # random + policy samples each
        # offline input (same contract as BCConfig.offline_data)
        self.input_ = None
        self.input_format = "parquet"
        self.updates_per_iteration = 20
        self.num_steps_sampled_before_learning_starts = 0

    def offline_data(self, *, input_=None, input_format=None,
                     updates_per_iteration=None) -> "CQLConfig":
        if input_ is not None:
            self.input_ = input_
        if input_format is not None:
            self.input_format = input_format
        if updates_per_iteration is not None:
            self.updates_per_iteration = updates_per_iteration
        return self


class CQLLearner(SACLearner):
    def critic_penalty(self, q1p, q2p, obs, actions, dist_inputs, key):
        """CQL(H): E_s[logsumexp_a Q(s,a)] - E_(s,a)~D[Q(s,a)], both critics.

        Out-of-distribution actions = uniform random over the action range
        plus fresh policy samples (importance-corrected as in the paper's
        implementation)."""
        cfg = self.config
        module = self.module
        dist = module.action_dist
        n = cfg.num_penalty_actions
        B = obs.shape[0]
        act_dim = module.action_dim
        scale = getattr(module, "action_scale", 1.0)
        k_rand, k_pi = jax.random.split(key)

        rand_acts = jax.random.uniform(
            k_rand, (n, B, act_dim), minval=-scale, maxval=scale)
        rand_logp = jnp.full((n, B), -act_dim * jnp.log(2.0 * scale))
        pi_keys = jax.random.split(k_pi, n)
        pi_samples = [dist.sample_with_logp(k, dist_inputs) for k in pi_keys]
        pi_acts = jnp.stack([a for a, _ in pi_samples])
        pi_logp = jnp.stack([lp for _, lp in pi_samples])

        all_acts = jnp.concatenate([rand_acts, pi_acts])          # (2n, B, A)
        all_logp = jnp.concatenate([rand_logp, pi_logp])          # (2n, B)

        def penalty_for(qp):
            q = jax.vmap(lambda a: module.q_values(qp, obs, a))(all_acts)
            # Importance correction: logsumexp over proposals q - logp.
            ood = jax.nn.logsumexp(q - jax.lax.stop_gradient(all_logp), axis=0)
            data_q = module.q_values(qp, obs, actions)
            return jnp.mean(ood) - jnp.mean(data_q)

        return cfg.min_q_weight * (penalty_for(q1p) + penalty_for(q2p))


class CQL(SAC):
    """Offline: replay buffer replaced by the recorded dataset."""

    learner_class = CQLLearner
    config_class = CQLConfig

    def setup(self, config) -> None:
        super().setup(config)
        cfg = self.algo_config
        assert cfg.input_ is not None, \
            "offline algorithms need .offline_data(input_=...)"
        self.offline = OfflineData(cfg.input_, format=cfg.input_format,
                                   seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        results: Dict[str, Any] = {}
        for _ in range(max(1, cfg.updates_per_iteration)):
            batch = self.offline.sample(cfg.train_batch_size)
            results = self.learner_group.update_from_batch(batch)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return {"learners": results, "dataset_size": self.offline.size}
