"""SingleAgentEpisode — trajectory container.

(ref: rllib/env/single_agent_episode.py SingleAgentEpisode — observations
have len T+1, actions/rewards len T; cut()/finalize() for fragment handoff.)
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import numpy as np


class SingleAgentEpisode:
    def __init__(self, id_: Optional[str] = None,
                 observations: Optional[List[Any]] = None):
        self.id_ = id_ or uuid.uuid4().hex[:16]
        self.observations: List[Any] = list(observations or [])
        self.actions: List[Any] = []
        self.rewards: List[float] = []
        self.extra: Dict[str, List[Any]] = {}  # e.g. action_logp per step
        self.is_terminated = False
        self.is_truncated = False
        #: return accumulated across previous fragments of the same episode
        #: (an episode cut at a rollout-fragment boundary continues in the
        #: next fragment with the same id).
        self._prev_return = 0.0
        self._prev_len = 0

    # ------------------------------------------------------------------
    def add_env_reset(self, observation) -> None:
        self.observations.append(observation)

    def add_env_step(self, observation, action, reward, *, terminated=False,
                     truncated=False, extra: Optional[Dict[str, Any]] = None) -> None:
        assert not self.is_done, "cannot extend a finished episode"
        self.observations.append(observation)
        self.actions.append(action)
        self.rewards.append(float(reward))
        if extra:
            for k, v in extra.items():
                self.extra.setdefault(k, []).append(v)
        self.is_terminated = bool(terminated)
        self.is_truncated = bool(truncated)

    # ------------------------------------------------------------------
    @property
    def is_done(self) -> bool:
        return self.is_terminated or self.is_truncated

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def total_len(self) -> int:
        return self._prev_len + len(self)

    def get_return(self) -> float:
        return float(sum(self.rewards))

    @property
    def total_return(self) -> float:
        return self._prev_return + self.get_return()

    def cut(self) -> "SingleAgentEpisode":
        """Chop at the current step: self becomes the finished fragment, the
        returned successor continues the episode from the last observation
        (ref: single_agent_episode.py cut())."""
        successor = SingleAgentEpisode(id_=self.id_,
                                       observations=[self.observations[-1]])
        successor._prev_return = self.total_return
        successor._prev_len = self.total_len
        return successor

    # ------------------------------------------------------------------
    def to_numpy(self) -> Dict[str, np.ndarray]:
        out = {
            "obs": np.asarray(self.observations, np.float32),  # (T+1, ...)
            "actions": np.asarray(self.actions),
            "rewards": np.asarray(self.rewards, np.float32),
        }
        for k, v in self.extra.items():
            out[k] = np.asarray(v)
        return out

    def __repr__(self) -> str:
        return (f"SingleAgentEpisode(id={self.id_}, len={len(self)}, "
                f"return={self.get_return():.1f}, done={self.is_done})")
