from ray_tpu.rl.env.env_runner import SingleAgentEnvRunner, env_spaces
from ray_tpu.rl.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rl.env.episode import SingleAgentEpisode

__all__ = ["SingleAgentEnvRunner", "EnvRunnerGroup", "SingleAgentEpisode",
           "env_spaces"]
