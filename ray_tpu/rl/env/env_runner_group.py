"""EnvRunnerGroup — fan-out over remote env-runner actors.

(ref: rllib/env/env_runner_group.py:71 EnvRunnerGroup — manages N remote
EnvRunner actors + an optional local one; foreach_env_runner fan-out,
sync_weights, restart of failed runners.)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.rl.env.env_runner import SingleAgentEnvRunner


class EnvRunnerGroup:
    def __init__(self, *, env, env_config, module_spec, num_env_runners: int,
                 num_envs_per_env_runner: int, rollout_fragment_length: int,
                 explore: bool = True, seed: int = 0):
        self._runner_kwargs = dict(
            env=env, env_config=env_config, module_spec=module_spec,
            num_envs=num_envs_per_env_runner,
            rollout_fragment_length=rollout_fragment_length,
            explore=explore, seed=seed,
        )
        self.num_env_runners = num_env_runners
        self._local_runner: Optional[SingleAgentEnvRunner] = None
        self._remote_runners: List[Any] = []
        if num_env_runners == 0:
            self._local_runner = SingleAgentEnvRunner(worker_index=0,
                                                      **self._runner_kwargs)
        else:
            cls = ray_tpu.remote(SingleAgentEnvRunner)
            self._remote_runners = [
                cls.remote(worker_index=i + 1, **self._runner_kwargs)
                for i in range(num_env_runners)
            ]

    # ------------------------------------------------------------------
    def sample(self, *, num_timesteps: Optional[int] = None,
               num_episodes: Optional[int] = None,
               random_actions: bool = False,
               explore: Optional[bool] = None) -> List:
        """Synchronous fan-out sample (ref: algorithm.py:1814
        synchronous_parallel_sample)."""
        if self._local_runner is not None:
            return self._local_runner.sample(
                num_timesteps=num_timesteps, num_episodes=num_episodes,
                random_actions=random_actions, explore=explore)
        n = len(self._remote_runners)
        refs = []
        for i, r in enumerate(self._remote_runners):
            # Spread the remainder over the first runners so the totals add
            # up to exactly num_timesteps / num_episodes.
            per_ts = per_eps = None
            if num_timesteps is not None:
                per_ts = num_timesteps // n + (1 if i < num_timesteps % n else 0)
            if num_episodes is not None:
                per_eps = num_episodes // n + (1 if i < num_episodes % n else 0)
            if per_ts == 0 or per_eps == 0:
                continue
            refs.append(r.sample.remote(num_timesteps=per_ts,
                                        num_episodes=per_eps,
                                        random_actions=random_actions,
                                        explore=explore))
        episodes: List = []
        for chunk in ray_tpu.get(refs):
            episodes.extend(chunk)
        return episodes

    def async_sample_refs(self, *, num_timesteps: int) -> List:
        """One in-flight sample ref per runner (IMPALA-style async path)."""
        assert self._remote_runners, "async sampling needs remote env runners"
        per = max(1, num_timesteps // len(self._remote_runners))
        return [r.sample.remote(num_timesteps=per) for r in self._remote_runners]

    # ------------------------------------------------------------------
    def sync_weights(self, params: Any, block: bool = True) -> None:
        """Push learner params to every runner (ref: env_runner_group.py
        sync_weights).  ``block=False`` is the async-pipeline mode: actor
        mailbox ordering still applies the weights before the runner's next
        sample call, but the caller doesn't stall on the round-trip."""
        if self._local_runner is not None:
            self._local_runner.set_state({"params": params})
            return
        # Snapshot ONCE per broadcast before fan-out: the learner's jitted
        # update donates its param/opt buffers (donate_argnums), so the live
        # tree handed to us is INVALIDATED the moment the learner steps
        # again — but actor-task args are held by reference until each
        # runner's set_state actually serializes/copies them.  Host-side
        # numpy copies are immune to donation (and serialize cheaply).
        import jax

        params = jax.tree.map(
            lambda x: jax.device_get(x) if hasattr(x, "dtype") else x,
            params)
        refs = [r.set_state.remote({"params": params})
                for r in self._remote_runners]
        if block:
            ray_tpu.get(refs)
            return
        # Double-buffered: hold this broadcast's refs and settle the
        # PREVIOUS one (usually done by now — mailbox order), so dropped
        # refs never race their own result into an unfreeable store entry.
        # Settling is non-blocking (wait timeout=0): one wedged runner must
        # not stall the fire-and-forget learner loop; unfinished refs carry
        # forward with a deadline instead.
        import time as _time

        prev = getattr(self, "_pending_sync", None)
        self._pending_sync = refs
        pend = getattr(self, "_unsettled", None)
        if pend is None:
            pend = self._unsettled = []
            self.sync_failures = 0
        if prev:
            pend.extend((r, _time.monotonic() + 10.0) for r in prev)
        self._sweep_unsettled()

    def _sweep_unsettled(self) -> None:
        import sys
        import time as _time

        still = []
        failed = 0
        for ref, deadline in self._unsettled:
            done, _ = ray_tpu.wait([ref], timeout=0)
            if done:
                try:
                    ray_tpu.get(ref)
                except Exception as e:  # noqa: BLE001
                    failed += 1
                    print(f"[env_runner_group] weight broadcast failed: "
                          f"{e!r}", file=sys.stderr, flush=True)
            elif _time.monotonic() > deadline:
                # A runner that can't apply weights samples with STALE
                # params forever — surface it instead of silently eating it.
                failed += 1
                print("[env_runner_group] weight broadcast unacknowledged "
                      "for 10s (wedged runner?)", file=sys.stderr, flush=True)
            else:
                still.append((ref, deadline))
        self._unsettled = still
        if failed:
            self.sync_failures += failed
            if self.sync_failures >= 3 * max(1, len(self._remote_runners)):
                raise RuntimeError(
                    f"{self.sync_failures} weight broadcasts failed or went "
                    "unacknowledged: runners are sampling with stale params "
                    "(see stderr for per-runner causes)")
        else:
            # Any failure-free sweep resets the consecutive count — refs
            # merely still in flight (sync interval < settle latency) must
            # not let rare recovered blips accumulate into a spurious raise
            # over a multi-day run.
            self.sync_failures = 0

    def foreach_env_runner(self, fn_name: str, *args, **kwargs) -> List[Any]:
        if self._local_runner is not None:
            return [getattr(self._local_runner, fn_name)(*args, **kwargs)]
        return ray_tpu.get([
            getattr(r, fn_name).remote(*args, **kwargs)
            for r in self._remote_runners
        ])

    def get_metrics(self) -> List[Dict[str, Any]]:
        return self.foreach_env_runner("get_metrics")

    @property
    def runners(self) -> List[Any]:
        return self._remote_runners

    def stop(self) -> None:
        # Settle the final broadcast so its refs don't leak store entries;
        # bounded wait — a wedged runner must not block shutdown.
        pending = getattr(self, "_pending_sync", None)
        if pending:
            self._pending_sync = None
            try:
                ray_tpu.wait(pending, num_returns=len(pending), timeout=2.0)
            except Exception:
                pass
        if self._local_runner is not None:
            self._local_runner.stop()
        for r in self._remote_runners:
            try:
                ray_tpu.get(r.stop.remote(), timeout=2.0)
                ray_tpu.kill(r)
            except Exception:
                pass
