"""SingleAgentEnvRunner — vectorized env sampling with RLModule inference.

(ref: rllib/env/single_agent_env_runner.py:64 SingleAgentEnvRunner —
gymnasium vector env step loop driving RLModule.forward_exploration;
sample(num_timesteps | num_episodes), get_state/set_state weight sync.)

TPU-native redesign: the policy forward over all envs' observations is ONE
jitted batched call (obs stacked host-side, categorical sampling inside the
jit via a threaded PRNG key), so per-step device work is a single dispatch
regardless of num_envs.  Envs are stepped with immediate-reset semantics
(no gymnasium autoreset edge cases in the batch).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.rl_module import Columns, RLModuleSpec
from ray_tpu.rl.env.episode import SingleAgentEpisode


def _make_env(env: Union[str, Callable], env_config: Dict[str, Any]):
    if callable(env):
        return env(env_config)
    import gymnasium as gym

    return gym.make(env, **env_config)


def env_spaces(env: Union[str, Callable], env_config: Dict[str, Any]):
    """(obs_dim, action_dim, discrete) probed from one throwaway env."""
    e = _make_env(env, env_config)
    try:
        import gymnasium as gym

        obs_dim = int(np.prod(e.observation_space.shape))
        if isinstance(e.action_space, gym.spaces.Discrete):
            return obs_dim, int(e.action_space.n), True
        return obs_dim, int(np.prod(e.action_space.shape)), False
    finally:
        e.close()


class SingleAgentEnvRunner:
    """Runs num_envs envs; one jitted policy call per vector step."""

    def __init__(self, *, env: Union[str, Callable],
                 env_config: Optional[Dict[str, Any]] = None,
                 module_spec: RLModuleSpec,
                 num_envs: int = 1,
                 rollout_fragment_length: int = 200,
                 explore: bool = True,
                 seed: int = 0,
                 worker_index: int = 0):
        self.env_config = dict(env_config or {})
        self.num_envs = num_envs
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        self.worker_index = worker_index
        self.module = module_spec.build()
        self._params = self.module.init_params(
            jax.random.key(seed * 1000 + worker_index))
        self._key = jax.random.key(seed * 7919 + worker_index + 1)
        self._weights_seq = 0

        self.envs = [_make_env(env, self.env_config) for _ in range(num_envs)]
        self.episodes: List[SingleAgentEpisode] = []
        self._done_episode_returns: List[float] = []
        self._done_episode_lens: List[int] = []
        for i, e in enumerate(self.envs):
            obs, _ = e.reset(seed=seed * 100003 + worker_index * 1000 + i)
            ep = SingleAgentEpisode()
            ep.add_env_reset(np.asarray(obs, np.float32).ravel())
            self.episodes.append(ep)

        dist = self.module.action_dist

        @jax.jit
        def _explore_step(params, key, obs):
            out = self.module.forward_exploration(params, obs)
            inputs = out[Columns.ACTION_DIST_INPUTS]
            key, sub = jax.random.split(key)
            actions = dist.sample(sub, inputs)
            logp = dist.logp(inputs, actions)
            return key, actions, logp

        @jax.jit
        def _greedy_step(params, obs):
            out = self.module.forward_inference(params, obs)
            inputs = out[Columns.ACTION_DIST_INPUTS]
            actions = dist.deterministic(inputs)
            return actions, dist.logp(inputs, actions)

        self._explore_step = _explore_step
        self._greedy_step = _greedy_step

    # ------------------------------------------------------------------
    def sample(self, *, num_timesteps: Optional[int] = None,
               num_episodes: Optional[int] = None,
               random_actions: bool = False,
               explore: Optional[bool] = None) -> List[SingleAgentEpisode]:
        """Collect fragments totalling num_timesteps (across the vector), or
        num_episodes full episodes (ref: single_agent_env_runner.py sample())."""
        if num_timesteps is None and num_episodes is None:
            num_timesteps = self.rollout_fragment_length * self.num_envs
        explore = self.explore if explore is None else explore

        out: List[SingleAgentEpisode] = []
        steps = 0
        episodes_done = 0
        while True:
            obs = np.stack([ep.observations[-1] for ep in self.episodes])
            if random_actions:
                actions, logps = self._random_actions(obs)
            elif explore:
                self._key, a, lp = self._explore_step(self._params, self._key, obs)
                actions, logps = np.asarray(a), np.asarray(lp)
            else:
                a, lp = self._greedy_step(self._params, obs)
                actions, logps = np.asarray(a), np.asarray(lp)

            for i, env in enumerate(self.envs):
                act = actions[i]
                if self.module.discrete:
                    act = int(act)
                next_obs, reward, terminated, truncated, _ = env.step(act)
                ep = self.episodes[i]
                ep.add_env_step(
                    np.asarray(next_obs, np.float32).ravel(), actions[i], reward,
                    terminated=terminated, truncated=truncated,
                    extra={Columns.ACTION_LOGP: float(logps[i])},
                )
                steps += 1
                if ep.is_done:
                    episodes_done += 1
                    self._done_episode_returns.append(ep.total_return)
                    self._done_episode_lens.append(ep.total_len)
                    out.append(ep)
                    reset_obs, _ = env.reset()
                    new_ep = SingleAgentEpisode()
                    new_ep.add_env_reset(np.asarray(reset_obs, np.float32).ravel())
                    self.episodes[i] = new_ep
            if num_episodes is not None:
                if episodes_done >= num_episodes:
                    break
            elif steps >= num_timesteps:
                break

        if num_episodes is None:
            # Hand off in-progress fragments too (PPO-style fixed batch).
            for i, ep in enumerate(self.episodes):
                if len(ep) > 0:
                    out.append(ep)
                    self.episodes[i] = ep.cut()
        return out

    def _random_actions(self, obs):
        n = len(self.envs)
        if self.module.discrete:
            acts = np.array([e.action_space.sample() for e in self.envs])
            logps = np.full((n,), -np.log(self.module.action_dim), np.float32)
        else:
            acts = np.stack([e.action_space.sample() for e in self.envs])
            logps = np.zeros((n,), np.float32)
        return acts, logps

    # ------------------------------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        """Drain per-episode stats (ref: env runner metrics via MetricsLogger)."""
        returns, lens = self._done_episode_returns, self._done_episode_lens
        self._done_episode_returns, self._done_episode_lens = [], []
        if not returns:
            return {"num_episodes": 0}
        return {
            "num_episodes": len(returns),
            "episode_return_mean": float(np.mean(returns)),
            "episode_return_max": float(np.max(returns)),
            "episode_return_min": float(np.min(returns)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def reset(self) -> None:
        """Reset all envs and discard in-progress episodes (used between
        evaluation rounds so no trajectory spans two policies)."""
        for i, env in enumerate(self.envs):
            obs, _ = env.reset()
            ep = SingleAgentEpisode()
            ep.add_env_reset(np.asarray(obs, np.float32).ravel())
            self.episodes[i] = ep
        self._done_episode_returns, self._done_episode_lens = [], []

    def get_state(self) -> Dict[str, Any]:
        return {"params": self._params, "weights_seq": self._weights_seq}

    def set_state(self, state: Dict[str, Any]) -> None:
        if "params" in state:
            # Copy on receipt: the learner's jitted update donates its param
            # buffers, so holding its live arrays across a weight sync would
            # leave this runner with deleted buffers (real on TPU; CPU's
            # donation no-op masks it).
            self._params = jax.tree.map(
                lambda x: jnp.array(x, copy=True) if hasattr(x, "dtype") else x,
                state["params"])
        self._weights_seq = state.get("weights_seq", self._weights_seq + 1)

    def ping(self) -> str:
        return "pong"

    def stop(self) -> None:
        for e in self.envs:
            e.close()
