"""MultiAgentEnvRunner — samples a MultiAgentEnv with per-module inference.

(ref: rllib/env/multi_agent_env_runner.py MultiAgentEnvRunner — steps the
env with a MultiRLModule, routing each agent's observation through its
mapped module via the policy_mapping_fn.)

TPU-native shape: agents are grouped by module each step, so device work is
one jitted batched forward PER MODULE per step (not per agent).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.core.multi_rl_module import MultiRLModuleSpec
from ray_tpu.rl.core.rl_module import Columns
from ray_tpu.rl.env.multi_agent_episode import MultiAgentEpisode


class MultiAgentEnvRunner:
    def __init__(self, *, env: Union[type, Callable],
                 env_config: Optional[Dict[str, Any]] = None,
                 module_spec: MultiRLModuleSpec,
                 policy_mapping_fn: Callable[[str], str],
                 rollout_fragment_length: int = 200,
                 explore: bool = True,
                 seed: int = 0,
                 worker_index: int = 0):
        self.env = env(env_config or {}) if callable(env) else env
        self.module = module_spec.build()
        self.policy_mapping_fn = policy_mapping_fn
        self.rollout_fragment_length = rollout_fragment_length
        self.explore = explore
        self._params = self.module.init_params(
            jax.random.key(seed * 1000 + worker_index))
        self._key = jax.random.key(seed * 7919 + worker_index + 1)
        self._seed = seed
        self._episode: Optional[MultiAgentEpisode] = None
        self._obs: Dict[str, Any] = {}
        self._done_returns: List[float] = []
        self._done_lens: List[int] = []

        # One jitted explore/greedy step per module.
        self._explore_steps: Dict[str, Any] = {}
        self._greedy_steps: Dict[str, Any] = {}
        for mid in self.module.keys():
            mod = self.module[mid]
            dist = mod.action_dist

            def make(mod=mod, dist=dist):
                @jax.jit
                def _explore(params, key, obs):
                    out = mod.forward_exploration(params, obs)
                    inputs = out[Columns.ACTION_DIST_INPUTS]
                    key, sub = jax.random.split(key)
                    actions = dist.sample(sub, inputs)
                    return key, actions, dist.logp(inputs, actions)

                @jax.jit
                def _greedy(params, obs):
                    out = mod.forward_inference(params, obs)
                    inputs = out[Columns.ACTION_DIST_INPUTS]
                    actions = dist.deterministic(inputs)
                    return actions, dist.logp(inputs, actions)

                return _explore, _greedy

            self._explore_steps[mid], self._greedy_steps[mid] = make()
        self._reset_env(seed)

    # ------------------------------------------------------------------
    def _reset_env(self, seed: Optional[int] = None) -> None:
        obs, _ = self.env.reset(seed=seed)
        mapping = {a: self.policy_mapping_fn(a) for a in obs}
        self._episode = MultiAgentEpisode(agent_to_module=mapping)
        self._episode.add_env_reset(obs)
        self._obs = obs

    def sample(self, *, num_timesteps: Optional[int] = None,
               num_episodes: Optional[int] = None,
               random_actions: bool = False,
               explore: Optional[bool] = None) -> List[MultiAgentEpisode]:
        explore = self.explore if explore is None else explore
        if num_timesteps is None and num_episodes is None:
            num_timesteps = self.rollout_fragment_length
        out: List[MultiAgentEpisode] = []
        env_steps = 0
        episodes_done = 0
        while True:
            actions, extras = self._compute_actions(
                self._obs, random_actions, explore)
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            self._episode.add_env_step(
                obs, actions, rewards, terminateds=terms, truncateds=truncs,
                extras=extras)
            env_steps += 1
            # Late joiners need a module assignment before they first act.
            for a in obs:
                if a not in self._episode.agent_to_module:
                    self._episode.agent_to_module[a] = self.policy_mapping_fn(a)
            # Next step acts only for agents still alive with an observation.
            self._obs = {a: o for a, o in obs.items()
                         if not (terms.get(a) or truncs.get(a))}
            if self._episode.is_done or not self._obs:
                episodes_done += 1
                self._done_returns.append(self._episode.total_return)
                self._done_lens.append(len(self._episode))
                out.append(self._episode)
                self._reset_env()
            if num_episodes is not None:
                if episodes_done >= num_episodes:
                    break
            elif env_steps >= num_timesteps:
                break
        if num_episodes is None and len(self._episode) > 0:
            # Hand off the in-progress fragment; continue from the last obs.
            out.append(self._episode)
            cut = MultiAgentEpisode(
                agent_to_module=dict(self._episode.agent_to_module))
            for agent, ep in self._episode.agent_episodes.items():
                if not ep.is_done:
                    cut.agent_episodes[agent] = ep.cut()
            self._episode = cut
        return out

    def _compute_actions(self, obs: Dict[str, Any], random_actions: bool,
                         explore: bool):
        actions: Dict[str, Any] = {}
        extras: Dict[str, Dict[str, Any]] = {}
        if not obs:
            return actions, extras
        if random_actions:
            for a in obs:
                actions[a] = self.env.action_spaces[a].sample()
                extras[a] = {Columns.ACTION_LOGP: 0.0}
            return actions, extras
        # Group agents by module: one batched jitted call per module.
        by_module: Dict[str, List[str]] = {}
        for a in obs:
            by_module.setdefault(
                self._episode.agent_to_module.get(
                    a, self.policy_mapping_fn(a)), []).append(a)
        for mid, agents in by_module.items():
            batch = np.stack([np.asarray(obs[a], np.float32).ravel()
                              for a in agents])
            params = self._params[mid]
            if explore:
                self._key, acts, logps = self._explore_steps[mid](
                    params, self._key, batch)
            else:
                acts, logps = self._greedy_steps[mid](params, batch)
            acts, logps = np.asarray(acts), np.asarray(logps)
            mod = self.module[mid]
            for i, a in enumerate(agents):
                actions[a] = int(acts[i]) if mod.discrete else acts[i]
                extras[a] = {Columns.ACTION_LOGP: float(logps[i])}
        return actions, extras

    # ------------------------------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        returns, lens = self._done_returns, self._done_lens
        self._done_returns, self._done_lens = [], []
        if not returns:
            return {"num_episodes": 0}
        return {
            "num_episodes": len(returns),
            "episode_return_mean": float(np.mean(returns)),
            "episode_return_max": float(np.max(returns)),
            "episode_return_min": float(np.min(returns)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def reset(self) -> None:
        self._reset_env()
        self._done_returns, self._done_lens = [], []

    def get_state(self) -> Dict[str, Any]:
        return {"params": self._params}

    def set_state(self, state: Dict[str, Any]) -> None:
        if "params" in state:
            # Copy on receipt (learner updates donate their buffers).
            new = {}
            for mid, p in state["params"].items():
                new[mid] = jax.tree.map(
                    lambda x: jnp.array(x, copy=True)
                    if hasattr(x, "dtype") else x, p)
            self._params.update(new)

    def ping(self) -> str:
        return "pong"

    def stop(self) -> None:
        self.env.close()
