"""PixelGridworld — an offline-buildable pixel-observation environment.

Stand-in for the Atari/IMPALA pixel benchmarks (BASELINE config 5): this
image lacks ``ale_py``, so the CNN/pixel path is gated on a procedurally
generated gridworld rendered as an RGB image instead (ref:
rllib/tuned_examples/impala/ — the pixel workloads the reference gates
IMPALA on).

The agent (red pixel block) must reach the goal (green block) on an
``n x n`` grid; observations are (n*cell, n*cell, 3) uint8 images, actions
are the 4 moves.  Reward: +1 at the goal (terminates), -0.01 per step.
Short optimal paths + dense pixels make learning fast enough for a
CPU-only learning-gate test while still exercising a real conv encoder.
"""

from __future__ import annotations

import numpy as np

import gymnasium as gym  # required: spaces + Env are load-bearing


class PixelGridworld(gym.Env):
    metadata = {"render_modes": []}

    def __init__(self, n: int = 5, cell: int = 2, max_steps: int = 30,
                 shaped: bool = False, seed: int = 0):
        self.n = int(n)
        self.cell = int(cell)
        self.max_steps = int(max_steps)
        #: Dense distance shaping (+0.1 per step of progress toward the
        #: goal, -0.1 per step away): zero-sum on any closed loop, so the
        #: optimal policy is unchanged (potential-based shaping) while the
        #: pixel learning gate converges in CI-sized budgets.
        self.shaped = bool(shaped)
        side = self.n * self.cell
        self.observation_space = gym.spaces.Box(
            low=0, high=255, shape=(side, side, 3), dtype=np.uint8)
        self.action_space = gym.spaces.Discrete(4)
        self._rng = np.random.default_rng(seed)
        self._goal = (self.n - 1, self.n - 1)
        self._pos = (0, 0)
        self._t = 0

    # ----------------------------------------------------------------- gym
    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        while True:
            pos = (int(self._rng.integers(self.n)),
                   int(self._rng.integers(self.n)))
            if pos != self._goal:
                break
        self._pos = pos
        self._t = 0
        return self._render(), {}

    def _dist(self, pos) -> int:
        return abs(pos[0] - self._goal[0]) + abs(pos[1] - self._goal[1])

    def step(self, action: int):
        r, c = self._pos
        prev_dist = self._dist(self._pos)
        dr, dc = ((-1, 0), (1, 0), (0, -1), (0, 1))[int(action)]
        self._pos = (min(self.n - 1, max(0, r + dr)),
                     min(self.n - 1, max(0, c + dc)))
        self._t += 1
        terminated = self._pos == self._goal
        truncated = self._t >= self.max_steps and not terminated
        reward = 1.0 if terminated else -0.01
        if self.shaped:
            reward += 0.1 * (prev_dist - self._dist(self._pos))
        return self._render(), reward, terminated, truncated, {}

    def _render(self) -> np.ndarray:
        side = self.n * self.cell
        img = np.zeros((side, side, 3), np.uint8)

        def paint(rc, channel):
            r, c = rc
            img[r * self.cell:(r + 1) * self.cell,
                c * self.cell:(c + 1) * self.cell, channel] = 255

        paint(self._goal, 1)  # green goal
        paint(self._pos, 0)   # red agent (drawn over the goal if reached)
        return img

    def close(self):
        pass


def make_pixel_gridworld(config: dict) -> PixelGridworld:
    """Env factory for AlgorithmConfig.environment(make_pixel_gridworld)."""
    return PixelGridworld(**(config or {}))
