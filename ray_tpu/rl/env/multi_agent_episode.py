"""MultiAgentEpisode — per-agent trajectories under one env episode.

(ref: rllib/env/multi_agent_episode.py MultiAgentEpisode — maps agent ids to
their SingleAgentEpisode plus the agent→module assignment used to route
training data to the right policy.)
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.rl.env.episode import SingleAgentEpisode


class MultiAgentEpisode:
    def __init__(self, agent_to_module: Optional[Dict[str, str]] = None,
                 id_: Optional[str] = None):
        self.id_ = id_ or uuid.uuid4().hex[:16]
        self.agent_episodes: Dict[str, SingleAgentEpisode] = {}
        self.agent_to_module: Dict[str, str] = dict(agent_to_module or {})
        self.is_terminated = False
        self.is_truncated = False

    # ------------------------------------------------------------------
    def add_env_reset(self, observations: Dict[str, Any]) -> None:
        for agent, obs in observations.items():
            ep = self.agent_episodes.setdefault(agent, SingleAgentEpisode())
            ep.add_env_reset(obs)

    def add_env_step(self, observations: Dict[str, Any],
                     actions: Dict[str, Any], rewards: Dict[str, float],
                     *, terminateds: Dict[str, bool],
                     truncateds: Dict[str, bool],
                     extras: Optional[Dict[str, Dict[str, Any]]] = None) -> None:
        for agent, action in actions.items():
            if agent not in observations:
                continue  # env dropped the agent without a final obs
            ep = self.agent_episodes.get(agent)
            if ep is None or ep.is_done or not ep.observations:
                continue
            ep.add_env_step(
                observations[agent], action, rewards.get(agent, 0.0),
                terminated=terminateds.get(agent, False),
                truncated=truncateds.get(agent, False),
                extra=(extras or {}).get(agent))
        # Agents may JOIN mid-episode (documented MultiAgentEnv contract):
        # their first observation opens a fresh per-agent trajectory.
        for agent, obs in observations.items():
            if agent not in self.agent_episodes:
                ep = SingleAgentEpisode()
                ep.add_env_reset(obs)
                self.agent_episodes[agent] = ep
        self.is_terminated = bool(terminateds.get("__all__", False))
        self.is_truncated = bool(truncateds.get("__all__", False))

    # ------------------------------------------------------------------
    @property
    def is_done(self) -> bool:
        return self.is_terminated or self.is_truncated

    def __len__(self) -> int:
        """Env steps ≈ max agent trajectory length."""
        return max((len(ep) for ep in self.agent_episodes.values()), default=0)

    @property
    def total_env_steps(self) -> int:
        return sum(len(ep) for ep in self.agent_episodes.values())

    @property
    def total_return(self) -> float:
        return float(sum(ep.total_return
                         for ep in self.agent_episodes.values()))

    def episodes_by_module(self) -> Dict[str, List[SingleAgentEpisode]]:
        """Route agent trajectories to their modules for training."""
        out: Dict[str, List[SingleAgentEpisode]] = {}
        for agent, ep in self.agent_episodes.items():
            if len(ep) == 0:
                continue
            module_id = self.agent_to_module.get(agent, "default_policy")
            out.setdefault(module_id, []).append(ep)
        return out

    def __repr__(self) -> str:
        return (f"MultiAgentEpisode(id={self.id_}, "
                f"agents={list(self.agent_episodes)}, "
                f"return={self.total_return:.1f}, done={self.is_done})")
