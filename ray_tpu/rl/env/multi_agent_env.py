"""MultiAgentEnv API + MultiAgentCartPole example env.

(ref: rllib/env/multi_agent_env.py MultiAgentEnv — reset() -> per-agent obs
dict; step(action_dict) -> per-agent obs/reward/terminated/truncated/info
dicts where the terminated/truncated dicts carry an "__all__" key; example
env rllib/examples/envs/classes/multi_agent.py MultiAgentCartPole.)
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class MultiAgentEnv:
    """Per-agent dict-in / dict-out environment.

    Agents may come and go between steps: only agents present in the
    returned observation dict act on the next step.  ``terminateds`` /
    ``truncateds`` carry per-agent flags plus ``"__all__"``.
    """

    #: ids of agents that can ever appear (informational)
    possible_agents: Tuple[str, ...] = ()
    #: per-agent gymnasium spaces (used to derive module specs)
    observation_spaces: Dict[str, Any] = {}
    action_spaces: Dict[str, Any] = {}

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]) -> Tuple[
            Dict[str, Any], Dict[str, float], Dict[str, bool],
            Dict[str, bool], Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPole-v1 instances, one per agent
    (ref: rllib/examples/envs/classes/multi_agent.py MultiAgentCartPole —
    the reference's standard multi-agent learning-test env).

    The episode ends (``__all__``) when every sub-episode has ended; an
    agent whose pole fell stops receiving observations while the others
    continue.
    """

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        import gymnasium as gym

        config = config or {}
        self.num_agents = int(config.get("num_agents", 2))
        self.possible_agents = tuple(
            f"agent_{i}" for i in range(self.num_agents))
        self._envs = {a: gym.make("CartPole-v1") for a in self.possible_agents}
        self.observation_spaces = {
            a: e.observation_space for a, e in self._envs.items()}
        self.action_spaces = {
            a: e.action_space for a, e in self._envs.items()}
        self._done: Dict[str, bool] = {}

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = {}, {}
        for i, (a, e) in enumerate(self._envs.items()):
            o, info = e.reset(seed=None if seed is None else seed + i)
            obs[a] = np.asarray(o, np.float32)
            infos[a] = info
            self._done[a] = False
        return obs, infos

    def step(self, action_dict):
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for a, act in action_dict.items():
            if self._done.get(a, True):
                continue
            o, r, term, trunc, info = self._envs[a].step(int(act))
            # Final observation included even on termination, so the episode
            # can close with its bootstrap obs.
            obs[a] = np.asarray(o, np.float32)
            rewards[a] = float(r)
            terms[a] = bool(term)
            truncs[a] = bool(trunc)
            infos[a] = info
            self._done[a] = bool(term or trunc)
        terms["__all__"] = all(self._done.values())
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, infos

    def close(self) -> None:
        for e in self._envs.values():
            e.close()
