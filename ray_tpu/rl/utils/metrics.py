"""MetricsLogger — windowed metric aggregation.

(ref: rllib/utils/metrics/metrics_logger.py MetricsLogger — log_value/
log_dict with EMA or window reduction, nested key paths, reduce().)
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Optional

import numpy as np


class _Stat:
    def __init__(self, window: Optional[int] = None, reduce: str = "mean"):
        self.window = window
        self.reduce_method = reduce
        self.values: deque = deque(maxlen=window)

    def push(self, value) -> None:
        self.values.append(value)

    def peek(self):
        if not self.values:
            return None
        vals = list(self.values)
        if self.reduce_method == "mean":
            return float(np.mean(vals))
        if self.reduce_method == "sum":
            return float(np.sum(vals))
        if self.reduce_method == "max":
            return float(np.max(vals))
        if self.reduce_method == "min":
            return float(np.min(vals))
        return vals[-1]


class MetricsLogger:
    def __init__(self) -> None:
        self._stats: Dict[str, Dict[str, _Stat]] = {}

    def log_value(self, name: str, value, *, key: str = "", window: Optional[int] = None,
                  reduce: str = "mean") -> None:
        group = self._stats.setdefault(key, {})
        stat = group.get(name)
        if stat is None:
            stat = group[name] = _Stat(window=window, reduce=reduce)
        stat.push(value)

    def log_dict(self, metrics: Dict[str, Any], *, key: str = "",
                 window: Optional[int] = None, reduce: str = "mean") -> None:
        for name, value in metrics.items():
            if isinstance(value, (int, float, np.number)):
                self.log_value(name, value, key=key, window=window, reduce=reduce)

    def reduce(self, key: str = "") -> Dict[str, Any]:
        group = self._stats.get(key, {})
        return {name: stat.peek() for name, stat in group.items()
                if stat.peek() is not None}
