from ray_tpu.rl.utils.metrics import MetricsLogger
from ray_tpu.rl.utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer

__all__ = ["MetricsLogger", "ReplayBuffer", "PrioritizedReplayBuffer"]
