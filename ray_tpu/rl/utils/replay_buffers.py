"""Replay buffers (ref: rllib/utils/replay_buffers/ — ReplayBuffer,
PrioritizedEpisodeReplayBuffer; stored as flat transition columns here since
the JAX learner consumes column batches)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rl.core.rl_module import Columns


class ReplayBuffer:
    """Uniform FIFO transition buffer."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._store: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(batch[Columns.OBS])
        if not self._store:
            for k, v in batch.items():
                self._store[k] = np.zeros((self.capacity, *v.shape[1:]), v.dtype)
        for i in range(n):
            for k, v in batch.items():
                self._store[k][self._next] = v[i]
            self._next = (self._next + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (ref: rllib/utils/replay_buffers/
    prioritized_episode_buffer.py; Schaul et al. 2015)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros((capacity,), np.float64)
        self._max_priority = 1.0
        self._last_idx: Optional[np.ndarray] = None

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(batch[Columns.OBS])
        start = self._next
        super().add(batch)
        for i in range(n):
            self._priorities[(start + i) % self.capacity] = self._max_priority

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        prios = self._priorities[:self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, batch_size, p=probs)
        self._last_idx = idx
        weights = (self._size * probs[idx]) ** (-self.beta)
        out = {k: v[idx] for k, v in self._store.items()}
        out[Columns.WEIGHTS] = (weights / weights.max()).astype(np.float32)
        return out

    def update_priorities(self, td_errors: np.ndarray) -> None:
        assert self._last_idx is not None
        prios = np.abs(td_errors) + 1e-6
        self._priorities[self._last_idx] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))
