"""Internal KV API (ref: python/ray/experimental/internal_kv.py —
_internal_kv_get/put/del/exists/list over the GCS KV tier).

Scope matches the reference's GCS-backed KV: **cluster-global**.  In the
driver/head process the store is local (lazily created); inside process
workers and ray:// drivers every call is routed over the nested-API
backchannel to the head's store, so all participants read and write the
same namespace (ref: gcs_kv_manager.h — one KV tier per cluster).
Persistence is opt-in via ``RAY_TPU_KV_PERSIST=1`` (or
``_system_config={"kv_persist": True}``), which writes a WAL under the
session dir so control-plane metadata survives a head restart (ref:
redis_store_client.h — the Redis-backed restartable GCS).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Union

from ray_tpu._private.kv_store import KVStore

_store: Optional[KVStore] = None
_lock = threading.Lock()


def _remote_call():
    """The head-routing callable when this process is a worker/client
    (its runtime proxies the nested API), else None (we ARE the head)."""
    from ray_tpu._private.runtime import runtime_or_none

    return getattr(runtime_or_none(), "kv_call", None)


def _get_store() -> KVStore:
    global _store
    with _lock:
        if _store is None:
            from ray_tpu._private.config import GLOBAL_CONFIG

            path = None
            if GLOBAL_CONFIG.kv_persist:
                path = os.path.join(GLOBAL_CONFIG.session_dir, "internal_kv.jsonl")
            _store = KVStore(persist_path=path)
        return _store


def _internal_kv_reset() -> None:
    """Testing hook: drop the in-memory store (the WAL, if any, remains —
    a fresh store replays it, which is exactly the restart path)."""
    global _store
    with _lock:
        if _store is not None:
            _store.close()  # don't leak the WAL fd across resets
        _store = None


def _as_bytes(v: Union[str, bytes]) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


def _internal_kv_initialized() -> bool:
    return True  # no external service to wait for


def _internal_kv_get(key: Union[str, bytes], *, namespace: str = "") -> Optional[bytes]:
    call = _remote_call()
    if call is not None:
        return call("get", _as_bytes(key), namespace)
    return _get_store().get(_as_bytes(key), namespace=namespace)


def _internal_kv_put(key: Union[str, bytes], value: Union[str, bytes],
                     overwrite: bool = True, *, namespace: str = "") -> bool:
    """Returns True when the key ALREADY EXISTED (whether or not it was then
    overwritten) — the reference's inverted contract, where GCS Put reports
    added=0 for any existing key."""
    call = _remote_call()
    if call is not None:
        return call("put", _as_bytes(key), _as_bytes(value), overwrite, namespace)
    newly_added = _get_store().put(_as_bytes(key), _as_bytes(value),
                                   overwrite=overwrite, namespace=namespace)
    return not newly_added


def _internal_kv_del(key: Union[str, bytes], *, namespace: str = "") -> int:
    call = _remote_call()
    if call is not None:
        return call("del", _as_bytes(key), namespace)
    return _get_store().delete(_as_bytes(key), namespace=namespace)


def _internal_kv_exists(key: Union[str, bytes], *, namespace: str = "") -> bool:
    call = _remote_call()
    if call is not None:
        return call("exists", _as_bytes(key), namespace)
    return _get_store().exists(_as_bytes(key), namespace=namespace)


def _internal_kv_list(prefix: Union[str, bytes], *, namespace: str = "") -> List[bytes]:
    call = _remote_call()
    if call is not None:
        return call("list", _as_bytes(prefix), namespace)
    return _get_store().keys(_as_bytes(prefix), namespace=namespace)
