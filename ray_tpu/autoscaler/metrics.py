"""Cluster-autoscaler metrics.

Declared at import time like the serve/train/ingest metric modules so
``scripts/check_metrics.py`` lints them; exported on ``/metrics`` through
the process registry (util/metrics.py).

The anchor set mirrors the reference's autoscaler dashboards: what the
policy decided and why (decisions by reason), what it actuated (node
launches/terminations by type), where the cluster sits against its
targets (target vs active node gauges), and the health gate
(quarantined nodes, postmortems consumed).
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge

DECISIONS = Counter(
    "ray_tpu_cluster_autoscale_decisions_total",
    "Cluster autoscale decisions applied, held, or rejected, by node type "
    "and outcome reason",
    tag_keys=("node_type", "reason"))

SCALE_UP = Counter(
    "ray_tpu_cluster_autoscale_scale_up_total",
    "Applied node-count target increases per node type",
    tag_keys=("node_type",))

SCALE_DOWN = Counter(
    "ray_tpu_cluster_autoscale_scale_down_total",
    "Applied node-count target decreases per node type",
    tag_keys=("node_type",))

TARGET_NODES = Gauge(
    "ray_tpu_cluster_target_nodes",
    "Current policy-set node-count target per node type",
    tag_keys=("node_type",))

ACTIVE_NODES = Gauge(
    "ray_tpu_cluster_active_nodes",
    "Active (requested/allocated/running) instances per node type, as "
    "observed at the last cluster-autoscaler tick",
    tag_keys=("node_type",))

QUARANTINED_NODES = Gauge(
    "ray_tpu_cluster_quarantined_nodes",
    "Nodes currently quarantined by the postmortem health gate (drained, "
    "excluded from placement, never refilled)")

QUARANTINES = Counter(
    "ray_tpu_cluster_quarantines_total",
    "Nodes quarantined after repeated crash/stall postmortems, by the "
    "postmortem reason that tipped the threshold",
    tag_keys=("reason",))

POSTMORTEMS_SEEN = Counter(
    "ray_tpu_cluster_health_postmortems_total",
    "Crash/stall postmortem rows consumed by the cluster health gate "
    "(node-attributed rows only)")
