from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    Monitor,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.elastic import (
    capacity_available,
    simulate_preemption,
    worker_capacity,
)
from ray_tpu.autoscaler.instance_manager import (
    Instance,
    InstanceManager,
    InstanceState,
    InstanceStorage,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    NodeProvider,
    SubprocessNodeProvider,
    TPUPodProvider,
)
from ray_tpu.autoscaler.policy import (
    ClusterAutoscaler,
    ClusterPolicyConfig,
    QuarantineTracker,
)
from ray_tpu.autoscaler.signals import (
    ClusterSignals,
    SignalCollector,
)

__all__ = [
    "Autoscaler", "AutoscalerConfig", "Monitor", "NodeTypeConfig",
    "NodeProvider", "FakeNodeProvider", "SubprocessNodeProvider",
    "TPUPodProvider", "Instance", "InstanceManager", "InstanceState",
    "InstanceStorage", "capacity_available", "simulate_preemption",
    "worker_capacity", "ClusterAutoscaler", "ClusterPolicyConfig",
    "QuarantineTracker", "ClusterSignals", "SignalCollector",
]
