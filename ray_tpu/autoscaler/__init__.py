from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    Monitor,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    NodeProvider,
    TPUPodProvider,
)

__all__ = [
    "Autoscaler", "AutoscalerConfig", "Monitor", "NodeTypeConfig",
    "NodeProvider", "FakeNodeProvider", "TPUPodProvider",
]
