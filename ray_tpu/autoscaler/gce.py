"""GCE TPU node provider over a mockable API client.

(ref: python/ray/autoscaler/_private/gcp/node_provider.py GCPNodeProvider —
create/terminate/list against the googleapiclient `tpu.projects.locations.
nodes` surface; _private/gcp/config.py resource naming.)

Offline twist: ``MockGCETPUAPI`` implements the same verbs, and its
"instances" are REAL ``ray_tpu worker`` OS processes joining the head over
the node server — so `ray_tpu up` with this provider exercises the whole
autoscaler -> provider -> cloud-API -> node-join path on one box.  Against
real GCP you swap the api object for one backed by googleapiclient; the
provider logic (naming, topology labels, slice packing, registration
waits) is identical.
"""

from __future__ import annotations

import subprocess
import threading
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


class MockGCETPUAPI:
    """The `projects.locations.nodes` verb surface, instances backed by
    real worker-node processes on this host."""

    def __init__(self, project: str = "mock-project",
                 zone: str = "us-central2-b"):
        self.project = project
        self.zone = zone
        self._instances: Dict[str, dict] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def _qualified(self, name: str) -> str:
        return (f"projects/{self.project}/locations/{self.zone}"
                f"/nodes/{name}")

    def create_node(self, name: str, accelerator_type: str, head_address: str,
                    num_cpus: float, resources: Dict[str, float],
                    labels: Dict[str, str], node_id: str) -> dict:
        """POST nodes.create — spawns the 'TPU VM' (a worker process)."""
        from ray_tpu.cluster_utils import worker_node_cmd, worker_node_env

        proc = subprocess.Popen(
            worker_node_cmd(head_address, num_cpus, resources, labels,
                            node_id),
            env=worker_node_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        record = {
            "name": self._qualified(name),
            "state": "CREATING",
            "acceleratorType": accelerator_type,
            "labels": dict(labels),
            "networkEndpoints": [{"ipAddress": "127.0.0.1"}],
            "metadata": {"node_id": node_id, "pid": proc.pid},
        }
        with self._lock:
            self._instances[name] = record
            self._procs[name] = proc
        return record

    def get_node(self, name: str) -> Optional[dict]:
        with self._lock:
            rec = self._instances.get(name)
            if rec is None:
                return None
            proc = self._procs.get(name)
            if rec["state"] in ("CREATING", "READY"):
                rec["state"] = ("READY" if proc is not None
                                and proc.poll() is None else "TERMINATED")
            return dict(rec)

    def delete_node(self, name: str) -> None:
        """DELETE nodes.delete — kills the instance process."""
        with self._lock:
            rec = self._instances.pop(name, None)
            proc = self._procs.pop(name, None)
        if rec is None:
            return
        if proc is not None:
            proc.kill()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass

    def list_nodes(self) -> List[dict]:
        with self._lock:
            names = list(self._instances)
        return [rec for rec in (self.get_node(n) for n in names)
                if rec is not None]


class GCETPUNodeProvider(NodeProvider):
    """Slice-aware GCE TPU provider: each created node is a TPU host VM
    with chips + topology labels; every ``hosts_per_slice`` hosts share an
    ici-slice label and the first host carries the pod-head resource (ref:
    gcp/node_provider.py + _private/accelerators/tpu.py:356)."""

    def __init__(self, project: str = "mock-project",
                 zone: str = "us-central2-b", accelerator: str = "v5e",
                 chips_per_host: int = 4, hosts_per_slice: int = 4,
                 api: Optional[MockGCETPUAPI] = None,
                 registration_timeout_s: float = 90.0):
        self.accelerator = accelerator
        self.chips_per_host = chips_per_host
        self.hosts_per_slice = hosts_per_slice
        self.registration_timeout_s = registration_timeout_s
        self._api = api or MockGCETPUAPI(project=project, zone=zone)
        self._node_ids: Dict[str, object] = {}  # instance -> scheduler id
        self._lock = threading.Lock()
        self._slice_counter = 0
        self._in_slice = 0

    # ------------------------------------------------------------- helpers
    @property
    def api(self) -> MockGCETPUAPI:
        return self._api

    def _head_address(self) -> str:
        from ray_tpu._private.runtime import get_runtime

        return get_runtime().start_node_server()

    def _slice_assignment(self):
        with self._lock:
            if self._in_slice >= self.hosts_per_slice:
                self._slice_counter += 1
                self._in_slice = 0
            first = self._in_slice == 0
            name = f"{self.accelerator}-slice-{self._slice_counter}"
            index = self._in_slice
            self._in_slice += 1
        return name, first, self._slice_counter, index

    def _slice_rollback(self, counter: int, index: int) -> None:
        """A host FAILED to come up: return its slice slot, or the retry of
        a slice's first host would never get the pod-head resource."""
        with self._lock:
            if self._slice_counter == counter and self._in_slice == index + 1:
                self._in_slice = index

    # ----------------------------------------------------------- interface
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        from ray_tpu._private.ids import NodeID
        from ray_tpu._private.runtime import get_runtime

        slice_name, first_in_slice, s_counter, s_index = \
            self._slice_assignment()
        pod_chips = self.chips_per_host * self.hosts_per_slice
        res = {k: float(v) for k, v in resources.items() if k != "CPU"}
        res["TPU"] = float(self.chips_per_host)
        if first_in_slice:
            # Pod-head resource: one per slice, the scheduling anchor for
            # "give me the whole slice" (ref: tpu.py:356-358).
            res[f"TPU-{self.accelerator}-{pod_chips}-head"] = 1.0
        node_labels = {
            **labels,
            "node-type": node_type,
            "ici-slice": slice_name,
            "accelerator-type": f"tpu-{self.accelerator}",
        }
        name = f"ray-{node_type}-{uuid.uuid4().hex[:8]}"
        node_id = NodeID.from_random()
        self._api.create_node(
            name, f"{self.accelerator}-{pod_chips}", self._head_address(),
            num_cpus=float(resources.get("CPU", 1.0)), resources=res,
            labels=node_labels, node_id=str(node_id))
        # The cloud API returns an operation; "done" here = the VM's worker
        # registered with the head (ref: GCPNodeProvider polling operations
        # + waiting for ray start on the VM).
        runtime = get_runtime()
        deadline = time.monotonic() + self.registration_timeout_s
        while time.monotonic() < deadline:
            node = runtime.scheduler.get_node(node_id)
            if node is not None and node.alive:
                break
            rec = self._api.get_node(name)
            if rec is None or rec["state"] == "TERMINATED":
                self._slice_rollback(s_counter, s_index)
                raise RuntimeError(
                    f"GCE TPU instance {name} died before registering")
            time.sleep(0.1)
        else:
            self._api.delete_node(name)
            self._slice_rollback(s_counter, s_index)
            raise TimeoutError(
                f"GCE TPU instance {name} did not register within "
                f"{self.registration_timeout_s}s")
        with self._lock:
            self._node_ids[name] = node_id
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self._api.delete_node(provider_node_id)
        with self._lock:
            self._node_ids.pop(provider_node_id, None)
        # The head's node-death handling reclaims the scheduler entry when
        # the connection drops — same path as a real VM disappearing.

    def non_terminated_nodes(self) -> List[str]:
        out = []
        for rec in self._api.list_nodes():
            if rec["state"] in ("CREATING", "READY"):
                out.append(rec["name"].rsplit("/", 1)[1])
        return out

    def scheduler_node_id(self, provider_node_id: str):
        with self._lock:
            return self._node_ids.get(provider_node_id)
