"""Cluster-signal collection for the node-tier autoscaler.

The sensing half of the cluster control loop (policy.py is the deciding
half): one :meth:`SignalCollector.collect` call snapshots every windowed
signal the :class:`~ray_tpu.autoscaler.policy.ClusterAutoscaler` composes
into node-count targets —

- **serve load**: cluster-wide request rate and mean router in-flight
  depth from the head :class:`~ray_tpu.util.metrics_agent
  .TimeSeriesAggregator` (the PR 12 accessors' rollup: subset-tag
  queries sum counters across deployments and average gauges), plus the
  SLO burn watchdog's alert state.
- **train pressure**: the data-starved fraction gauge and the count of
  unclaimed ingest shards across live streaming-ingest runs.
- **static demand**: the scheduler's blocked resource requests and
  pending placement-group bundles — the floor the pre-existing
  bin-packing autoscaler already serves.
- **health**: node-attributed crash/stall postmortem rows from the
  forensics stream, the quarantine gate's input.

Cross-layer reads probe ``sys.modules`` instead of importing (the
util.state idiom): an autoscaler in a cluster that never imported serve
or train must not drag those packages in just to read zeros.  All
queries are keyed on the caller-supplied ``now`` so the layer is
deterministic under test.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: Postmortem reasons that count against a node's health.  Deliberate
#: dumps (user trigger_dump, SIGUSR1 debugging) must not quarantine the
#: node they ran on.
HEALTH_REASONS = ("actor_death", "task_stall", "hang", "stall", "crash",
                  "worker_death", "node_death")


@dataclass
class ClusterSignals:
    """One sensing snapshot, all fields explicit so unit tests drive the
    policy with synthetic inputs (the serve PolicyInputs pattern)."""

    now: float
    #: Cluster-wide serve request rate (req/s) over the window.
    serve_request_rate: float = 0.0
    #: Mean in-flight requests across routers over the window.
    serve_inflight: float = 0.0
    #: Any serve deployment's SLO fast-window burn is alerting.
    slo_burn_alerting: bool = False
    #: Every window of every objective is under threshold.
    slo_burn_quiet: bool = True
    #: Fraction of recent step time the training loop spent data-starved.
    train_data_starved_fraction: float = 0.0
    #: Source shards not yet claimed by any reader across live ingests.
    pending_ingest_shards: int = 0
    #: Blocked resource requests + pending PG bundles (the binpack floor).
    static_demand: List[Dict[str, float]] = field(default_factory=list)
    #: Node-attributed health postmortems: [{"id", "ts", "reason", "node"}].
    postmortems: List[Dict[str, Any]] = field(default_factory=list)


class SignalCollector:
    """Gathers one :class:`ClusterSignals` snapshot per autoscaler tick."""

    def __init__(self, scheduler=None, window_s: float = 60.0):
        self.scheduler = scheduler
        self.window_s = window_s

    # ------------------------------------------------------------ sub-reads
    def _serve_signals(self, agg, now: float) -> Dict[str, Any]:
        out = {"rate": agg.window_rate("serve_requests_total", None,
                                       self.window_s, now),
               "inflight": agg.window_sum("serve_router_inflight", None,
                                          self.window_s, now),
               "alerting": False, "quiet": True}
        slo = sys.modules.get("ray_tpu.serve.slo")
        if slo is not None:
            try:
                payload = slo.get_watchdog().evaluate(now=now)
            except Exception:  # noqa: BLE001 — sensing must not kill the tick
                payload = {}
            for dep in payload.values():
                if dep.get("alerting"):
                    out["alerting"] = True
                for obj in dep.get("objectives", {}).values():
                    if obj.get("burn_fast", 0.0) >= obj.get(
                            "burn_threshold", float("inf")) \
                            or obj.get("burn_slow", 0.0) >= obj.get(
                                "burn_threshold", float("inf")) \
                            or obj.get("alerting"):
                        out["quiet"] = False
        return out

    def _train_starved_fraction(self, agg) -> float:
        if sys.modules.get("ray_tpu.train.metrics") is None:
            return 0.0
        return agg.latest("ray_tpu_train_data_starved_fraction", {}) or 0.0

    def _pending_ingest_shards(self) -> int:
        ingest = sys.modules.get("ray_tpu.data.ingest.ingest")
        if ingest is None:
            return 0
        try:
            return int(ingest.pending_shards())
        except Exception:  # noqa: BLE001
            return 0

    def _postmortems(self) -> List[Dict[str, Any]]:
        from ray_tpu.util import forensics

        rows = []
        for row in forensics.list_postmortems():
            reason = str(row.get("reason") or "")
            if row.get("node") and any(reason.startswith(r)
                                       for r in HEALTH_REASONS):
                rows.append({"id": row["id"], "ts": row.get("ts"),
                             "reason": reason, "node": str(row["node"])})
        return rows

    # -------------------------------------------------------------- collect
    def collect(self, now: Optional[float] = None) -> ClusterSignals:
        from ray_tpu.util.metrics_agent import get_aggregator

        t = time.time() if now is None else float(now)
        agg = get_aggregator()
        agg.sample_registry(ts=t)
        serve = self._serve_signals(agg, t)
        demand: List[Dict[str, float]] = []
        if self.scheduler is not None:
            demand = [dict(r) for r in self.scheduler.pending_demand()]
            for bundles in self.scheduler.pending_pg_demand():
                demand.extend(dict(b) for b in bundles)
        return ClusterSignals(
            now=t,
            serve_request_rate=serve["rate"],
            serve_inflight=serve["inflight"],
            slo_burn_alerting=serve["alerting"],
            slo_burn_quiet=serve["quiet"],
            train_data_starved_fraction=self._train_starved_fraction(agg),
            pending_ingest_shards=self._pending_ingest_shards(),
            static_demand=demand,
            postmortems=self._postmortems(),
        )
