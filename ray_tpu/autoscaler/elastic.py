"""Elastic-capacity signals + the simulated-preemption hook.

The training-side contract with the autoscaler (ROADMAP item 3): the
elastic Trainer needs exactly two things from the cluster layer —

* ``worker_capacity(bundle)`` — how many copies of a worker bundle the
  *live* cluster can host right now.  The trainer shrinks its world size
  to this after a preemption and grows back toward ``max_workers`` when
  the number recovers (checked every ``ElasticConfig.grow_check_period_s``).
  Capacity is computed against each node's TOTAL resources, not its
  instantaneous availability: between attempts the worker group's
  placement group is released, and a grow decision made against
  still-held resources would deadlock against the very group it is
  trying to replace.

* ``simulate_preemption(...)`` — the chaos hook that makes a TPU slice
  vanish the way real preemption does: every actor hosted on the victim
  node dies (``ActorDiedError`` surfaces to anyone awaiting their calls)
  and the node leaves the scheduler in the same stroke.  Real clusters
  get this for free from the cloud; tests, ``tests/chaos_utils.py`` and
  ``scripts/bench_elastic.py`` drive it through the ``preempt_node``
  fault point (ray_tpu._private.fault_injection).
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

logger = logging.getLogger(__name__)

Resources = Dict[str, float]


def _bundle_fits(total: Resources, bundle: Resources) -> int:
    """How many copies of ``bundle`` fit in ``total`` (0 when any key is
    missing)."""
    copies = None
    for key, need in bundle.items():
        if need <= 0:
            continue
        have = total.get(key, 0.0)
        n = int(have / need + 1e-9)
        copies = n if copies is None else min(copies, n)
    return 0 if copies is None else copies


def worker_capacity(bundle: Resources,
                    exclude_nodes: Optional[set] = None) -> int:
    """Total copies of ``bundle`` the live cluster can host, summed over
    alive nodes (against node totals — see module docstring)."""
    from ray_tpu._private.runtime import get_runtime

    exclude = {str(n) for n in (exclude_nodes or ())}
    capacity = 0
    for node in get_runtime().scheduler.nodes():
        if not node.alive or str(node.id) in exclude:
            continue
        capacity += _bundle_fits(node.total, bundle)
    return capacity


def capacity_available(bundle: Resources, want: int) -> bool:
    """True when the live cluster can host ``want`` copies of ``bundle``
    — the trainer's grow-back signal."""
    return worker_capacity(bundle) >= want


def actors_on_node(node_id) -> list:
    """ActorIDs of live actors hosted on ``node_id`` (virtual-node model:
    in-process actors carry the scheduler node their lease landed on)."""
    from ray_tpu._private.runtime import get_runtime

    runtime = get_runtime()
    want = str(node_id)
    out = []
    for aid, state in list(runtime._actors.items()):
        if state.state != "ALIVE":
            continue
        hosted = state.remote_node or state.node_id
        if hosted is not None and str(hosted) == want:
            out.append(aid)
    return out


def pick_preemptible_node(exclude_head: bool = True) -> Optional[str]:
    """A live node a preemption could take (never the head by default);
    None when the cluster has no candidate."""
    from ray_tpu._private.runtime import get_runtime

    runtime = get_runtime()
    head = str(runtime.head_node_id)
    for node in runtime.scheduler.nodes():
        if node.alive and (not exclude_head or str(node.id) != head):
            return str(node.id)
    return None


def simulate_preemption(node_id: Optional[str] = None,
                        exclude_head: bool = True) -> Optional[str]:
    """Preempt one node: kill every actor it hosts (no restart — a
    preempted slice does not come back as the same node), then remove the
    node from the scheduler.  Returns the preempted node id, or None when
    no candidate node exists (e.g. a single-head cluster with
    ``exclude_head``)."""
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.runtime import get_runtime

    runtime = get_runtime()
    if node_id is None:
        node_id = pick_preemptible_node(exclude_head=exclude_head)
        if node_id is None:
            return None
    victims = actors_on_node(node_id)
    for aid in victims:
        try:
            runtime.kill_actor(aid, no_restart=True)
        except Exception:  # already dying — the node removal still counts
            pass
    try:
        runtime.scheduler.remove_node(NodeID(str(node_id)))
    except Exception:
        pass
    from ray_tpu.train import metrics as train_metrics

    train_metrics.PREEMPTIONS.inc()
    logger.warning("simulated preemption: node %s (%d actor(s) killed)",
                   node_id, len(victims))
    return str(node_id)
