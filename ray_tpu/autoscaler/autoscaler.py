"""Declarative autoscaler: reconcile cluster size against resource demand.

Counterpart of the reference's autoscaler v2 (ref: python/ray/autoscaler/v2/
— autoscaler.py, scheduler.py, instance_manager/reconciler.py; v1
StandardAutoscaler:171 + Monitor:127 for the process model): one reconcile
pass reads (a) unmet resource demand — requests blocked in the scheduler —
and (b) pending placement-group bundles, bin-packs them onto configured node
types, launches what's missing (bounded by max_workers and upscaling speed),
and terminates nodes idle past the timeout (respecting min_workers).  The
`Monitor` thread is the reference's monitor.py loop.

State machine is deliberately reconciler-shaped (observe → diff → act), not
event-driven: the same pass works from a cold start, after a crash, or with
externally added nodes — the v2 design's point.  Every node the autoscaler
requests is an `Instance` with an explicit per-instance FSM and failure log
persisted to the session dir (instance_manager.py; ref: the v2
instance-storage reconciler, reconciler.py:53) — observed drift (a
provider node dying under a RUNNING instance) fails the instance, whose
freed slot the same pass's demand/min_workers arithmetic then replaces.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.instance_manager import (ACTIVE_STATES, Instance,
                                                 InstanceManager,
                                                 InstanceState,
                                                 InstanceStorage)
from ray_tpu.autoscaler.node_provider import NodeProvider

Resources = Dict[str, float]


@dataclass
class NodeTypeConfig:
    """(ref: cluster YAML available_node_types entries)."""

    resources: Resources
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)
    #: Cheap/interruptible capacity (the policy layer routes train-driven
    #: signals here and serve-driven signals to protected types; the
    #: elastic controller (PR 6) already survives losing these nodes).
    #: Stamped onto launched nodes as a ``preemptible`` label.
    preemptible: bool = False


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    #: Names the persisted instance table (ref: the v2 storage is
    #: per-cluster); two clusters in one session must not clobber or
    #: mis-adopt each other's instances.
    cluster_name: str = "default"
    #: Max nodes launched per reconcile pass (ref: upscaling_speed).
    max_launches_per_round: int = 100
    #: Cluster-wide worker cap across ALL node types (ref: the top-level
    #: max_workers in the cluster YAML); None = unbounded.
    max_total_workers: Optional[int] = None


class Autoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 scheduler=None, storage_path: Optional[str] = "auto"):
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.runtime import get_runtime

        self.config = config
        self.provider = provider
        self.scheduler = scheduler or get_runtime().scheduler
        self.scheduler.autoscaling_enabled = True
        self.scheduler.autoscaler_node_shapes = [
            dict(cfg.resources) for cfg in config.node_types.values()]
        if storage_path == "auto":
            import os

            storage_path = os.path.join(
                GLOBAL_CONFIG.session_dir,
                f"autoscaler-{config.cluster_name}-instances.json")
        self.im = InstanceManager(InstanceStorage(storage_path))
        #: Per-type node-count targets set by the policy layer
        #: (policy.ClusterAutoscaler).  A type with a target launches up
        #: to it and releases *idle* nodes above it without waiting for
        #: idle_timeout_s (the policy's hysteresis already provided the
        #: delay); a type without one keeps the pure demand/idle behavior.
        self.target_counts: Dict[str, int] = {}
        #: Serializes update()/_launch: the stale-REQUESTED sweep assumes
        #: no create_node is in flight, which only holds when reconcile
        #: passes (Monitor thread + any direct caller) are mutually
        #: exclusive.  RLock because update() calls _launch.
        self._reconcile_lock = threading.RLock()
        # Adoption: a restarted autoscaler keeps persisted instances whose
        # provider nodes still exist, and immediately fails the rest — a
        # stale table (crashed run, earlier cluster in the same session)
        # must not count against caps or block min_workers launches.
        # (If the provider is unreachable here, update()'s stale-REQUESTED
        # sweep and reconcile_drift finish the job on the first pass.)
        try:
            live = set(self.provider.non_terminated_nodes())
        except Exception:  # noqa: BLE001
            live = None
        if live is not None:
            for inst in self.im.instances(*ACTIVE_STATES):
                if inst.state == InstanceState.REQUESTED:
                    self.im.transition(inst, InstanceState.ALLOCATION_FAILED,
                                       "lost before allocation (restart)")
                elif inst.provider_node_id not in live:
                    self.im.transition(inst, InstanceState.FAILED,
                                       "provider node not found at adoption")

    # ------------------------------------------------------------ reconcile
    def update(self) -> dict:
        """One reconcile pass; returns {"launched": [...], "terminated":
        [...], "failed": [...]} (provider node ids / instance ids)."""
        with self._reconcile_lock:
            return self._update_locked()

    def _update_locked(self) -> dict:
        launched: List[str] = []
        terminated: List[str] = []

        # 1. Observe: cloud truth vs instance intent vs scheduler truth.
        live = set(self.provider.non_terminated_nodes())
        # update() is the only requester and _launch is synchronous, so any
        # REQUESTED instance visible here is a prior run's in-flight create
        # that never landed (crash between persist and allocate).
        for inst in self.im.instances(InstanceState.REQUESTED):
            self.im.transition(inst, InstanceState.ALLOCATION_FAILED,
                               "lost before allocation")
        failed = self.im.reconcile_drift(live, self.scheduler)
        # Leaked provider nodes: the cloud reports them alive but NO active
        # instance references them (a crash between create_node and the
        # ALLOCATED persist, or an instance failed at adoption while its
        # node survived).  Nothing else will ever terminate such a node —
        # it bills forever — so sweep it here.  Safe against racing
        # launches: _launch runs under the same reconcile lock, so every
        # in-flight create is already persisted by the time we observe.
        referenced = {inst.provider_node_id
                      for inst in self.im.instances(*ACTIVE_STATES)
                      if inst.provider_node_id}
        for pid in sorted(live - referenced):
            try:
                self.provider.terminate_node(pid)
                terminated.append(pid)
            except Exception:  # noqa: BLE001 — reappears next pass, resweep
                pass
        # ALLOCATED instances whose scheduler node came alive -> RUNNING.
        # The scheduler id can bind LATE: some providers only learn it once
        # the worker joins, so refresh the mapping each pass until it lands.
        for inst in self.im.instances(InstanceState.ALLOCATED):
            if inst.scheduler_node_id is None:
                sid = getattr(self.provider, "scheduler_node_id",
                              lambda _: None)(inst.provider_node_id)
                if sid is not None:
                    inst.scheduler_node_id = str(sid)
                    self.im.storage.upsert(inst)
            node = (self.scheduler.get_node(inst.scheduler_node_id)
                    if inst.scheduler_node_id is not None else None)
            if node is not None and node.alive:
                self.im.transition(inst, InstanceState.RUNNING,
                                   "scheduler node registered")

        # 2. min_workers floor (still subject to the cluster-wide cap).
        counts = self.im.active_counts()
        for type_name, cfg in self.config.node_types.items():
            for _ in range(cfg.min_workers - counts.get(type_name, 0)):
                if self._at_total_cap():
                    break
                pid = self._launch(type_name)
                if pid:
                    launched.append(pid)

        # 2b. Policy targets: launch up to each type's target count
        # (bounded by max_workers and the cluster-wide cap like any other
        # launch; static demand below remains the floor on top).
        counts = self.im.active_counts()
        for type_name, target in self.target_counts.items():
            cfg = self.config.node_types.get(type_name)
            if cfg is None:
                continue
            want = min(target, cfg.max_workers) - counts.get(type_name, 0)
            for _ in range(want):
                if self._at_total_cap() or \
                        len(launched) >= self.config.max_launches_per_round:
                    break
                pid = self._launch(type_name)
                if pid:
                    launched.append(pid)
                    counts[type_name] = counts.get(type_name, 0) + 1

        # 3. Unmet demand -> more nodes (simple first-fit-decreasing binpack
        # onto hypothetical new nodes, the v2 scheduler.py role).
        demand = list(self.scheduler.pending_demand())
        for bundles in self.scheduler.pending_pg_demand():
            demand.extend(bundles)
        for type_name, n in self._binpack(demand).items():
            cfg = self.config.node_types[type_name]
            counts = self.im.active_counts()
            room = cfg.max_workers - counts.get(type_name, 0)
            if self.config.max_total_workers is not None:
                # Cluster-wide cap binds across all types together.
                room = min(room, self.config.max_total_workers
                           - sum(counts.values()))
            for _ in range(min(n, room,
                               self.config.max_launches_per_round - len(launched))):
                pid = self._launch(type_name)
                if pid:
                    launched.append(pid)

        # 4. Idle nodes past timeout -> terminate (never below min_workers,
        # never a node with resources in use).
        now = time.time()
        counts = self.im.active_counts()
        for inst in self.im.instances(InstanceState.RUNNING):
            cfg = self.config.node_types.get(inst.node_type)
            if cfg is None or counts.get(inst.node_type, 0) <= cfg.min_workers:
                continue
            node = self._scheduler_node(inst)
            if node is None:
                continue
            busy = any(node.available.get(k, 0.0) < v
                       for k, v in node.total.items())
            if busy:
                continue
            # A policy target below the active count releases idle nodes
            # immediately — the policy's hysteresis already waited — but
            # NEVER a busy one: scale-down drains by attrition, not kill.
            target = self.target_counts.get(inst.node_type)
            over_target = (target is not None
                           and counts.get(inst.node_type, 0) > target)
            if over_target or now - node.last_busy > self.config.idle_timeout_s:
                self.im.transition(
                    inst, InstanceState.TERMINATING,
                    "over policy target" if over_target
                    else f"idle > {self.config.idle_timeout_s}s")
                counts[inst.node_type] -= 1
        # TERMINATING instances (this pass's AND earlier stuck ones): call
        # the provider; a failed call stays TERMINATING so the NEXT pass
        # retries — transitioning to terminal FAILED would leak a live,
        # billing cloud node that nothing references.
        for inst in self.im.instances(InstanceState.TERMINATING):
            try:
                self.provider.terminate_node(inst.provider_node_id)
                self.im.transition(inst, InstanceState.TERMINATED, "")
                terminated.append(inst.provider_node_id)
            except Exception as e:  # noqa: BLE001 — retried next pass
                inst.history.append(
                    [inst.state, time.time(), f"terminate failed: {e!r}"])
                self.im.storage.upsert(inst)
        self.im.storage.prune_terminal()
        return {"launched": launched, "terminated": terminated,
                "failed": [i.instance_id for i in failed]}

    # -------------------------------------------------------------- helpers
    def _at_total_cap(self) -> bool:
        cap = self.config.max_total_workers
        if cap is None:
            return False
        return sum(self.im.active_counts().values()) >= cap

    def _launch(self, type_name: str) -> Optional[str]:
        with self._reconcile_lock:
            return self._launch_locked(type_name)

    def _launch_locked(self, type_name: str) -> Optional[str]:
        cfg = self.config.node_types[type_name]
        inst = self.im.request(type_name)
        labels = dict(cfg.labels)
        if cfg.preemptible:
            labels["preemptible"] = "true"
        try:
            pid = self.provider.create_node(type_name, dict(cfg.resources),
                                            labels)
        except Exception as e:  # noqa: BLE001 — tracked per instance
            self.im.transition(inst, InstanceState.ALLOCATION_FAILED,
                               f"create_node: {e!r}")
            return None
        sched_id = getattr(self.provider, "scheduler_node_id",
                           lambda _: None)(pid)
        self.im.transition(inst, InstanceState.ALLOCATED, "provider created",
                           provider_node_id=pid,
                           scheduler_node_id=(str(sched_id)
                                              if sched_id else None))
        return pid

    def _scheduler_node(self, inst: Instance):
        node_id = inst.scheduler_node_id
        if node_id is None:
            node_id = getattr(self.provider, "scheduler_node_id",
                              lambda _: None)(inst.provider_node_id)
        if node_id is None:
            return None
        return self.scheduler.get_node(node_id)

    def _binpack(self, demand: List[Resources]) -> Dict[str, int]:
        """How many nodes of each type cover `demand` (first-fit decreasing;
        existing free capacity is NOT counted — demand is what's blocked
        *after* the scheduler already tried to place it)."""
        if not demand:
            return {}
        demand = sorted(demand,
                        key=lambda r: -sum(v for v in r.values()))
        bins: List[tuple] = []  # (type_name, remaining)
        need: Dict[str, int] = {}
        for req in demand:
            placed = False
            for type_name, remaining in bins:
                if all(remaining.get(k, 0.0) >= v for k, v in req.items()):
                    for k, v in req.items():
                        remaining[k] = remaining.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            # Open a new bin of the cheapest feasible type.
            for type_name, cfg in self.config.node_types.items():
                if all(cfg.resources.get(k, 0.0) >= v for k, v in req.items()):
                    remaining = dict(cfg.resources)
                    for k, v in req.items():
                        remaining[k] -= v
                    bins.append((type_name, remaining))
                    need[type_name] = need.get(type_name, 0) + 1
                    break
            # No feasible type: skip — the scheduler's feasibility check
            # already counts autoscaler_node_shapes, so such a request
            # raised InfeasibleError at submit instead of reaching here.
        return need


class Monitor:
    """Background reconcile loop (ref: _private/monitor.py Monitor:127)."""

    def __init__(self, autoscaler: Autoscaler, interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="ray_tpu_autoscaler", daemon=True)

    def start(self) -> "Monitor":
        self._thread.start()
        return self

    def _run(self) -> None:
        from ray_tpu.util import watchdog

        while not self._stop.wait(self.interval_s):
            # Beat BEFORE the pass: a reconcile wedged on a hung provider
            # goes beat-quiet, which is exactly what the hang watchdog's
            # flight-recorder dump should catch.
            watchdog.beat("cluster.monitor")
            try:
                self.autoscaler.update()
            except Exception:  # reconcile must survive transient errors
                import traceback

                traceback.print_exc()

    def stop(self) -> None:
        """Idempotent shutdown: join the tick thread (no reconcile pass —
        and therefore no launch — survives the return) and retire the
        monitor's watchdog source so a stopped monitor is not flagged as
        a hang."""
        from ray_tpu.util import watchdog

        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        watchdog.forget("cluster.monitor")
        self.autoscaler.scheduler.autoscaling_enabled = False
        self.autoscaler.scheduler.autoscaler_node_shapes = []
