"""Declarative autoscaler: reconcile cluster size against resource demand.

Counterpart of the reference's autoscaler v2 (ref: python/ray/autoscaler/v2/
— autoscaler.py, scheduler.py, instance_manager/reconciler.py; v1
StandardAutoscaler:171 + Monitor:127 for the process model): one reconcile
pass reads (a) unmet resource demand — requests blocked in the scheduler —
and (b) pending placement-group bundles, bin-packs them onto configured node
types, launches what's missing (bounded by max_workers and upscaling speed),
and terminates nodes idle past the timeout (respecting min_workers).  The
`Monitor` thread is the reference's monitor.py loop.

State machine is deliberately reconciler-shaped (observe → diff → act), not
event-driven: the same pass works from a cold start, after a crash, or with
externally added nodes — the v2 design's point.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

Resources = Dict[str, float]


@dataclass
class NodeTypeConfig:
    """(ref: cluster YAML available_node_types entries)."""

    resources: Resources
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    idle_timeout_s: float = 60.0
    #: Max nodes launched per reconcile pass (ref: upscaling_speed).
    max_launches_per_round: int = 100
    #: Cluster-wide worker cap across ALL node types (ref: the top-level
    #: max_workers in the cluster YAML); None = unbounded.
    max_total_workers: Optional[int] = None


class Autoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider,
                 scheduler=None):
        from ray_tpu._private.runtime import get_runtime

        self.config = config
        self.provider = provider
        self.scheduler = scheduler or get_runtime().scheduler
        self.scheduler.autoscaling_enabled = True
        self.scheduler.autoscaler_node_shapes = [
            dict(cfg.resources) for cfg in config.node_types.values()]
        #: provider node id -> node type name
        self._owned: Dict[str, str] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ reconcile
    def update(self) -> dict:
        """One reconcile pass; returns {"launched": [...], "terminated": [...]}."""
        launched: List[str] = []
        terminated: List[str] = []

        # 1. Observe: drop provider nodes that vanished out from under us.
        live = set(self.provider.non_terminated_nodes())
        with self._lock:
            for pid in list(self._owned):
                if pid not in live:
                    del self._owned[pid]

        # 2. min_workers floor (still subject to the cluster-wide cap).
        counts = self._counts()
        for type_name, cfg in self.config.node_types.items():
            for _ in range(cfg.min_workers - counts.get(type_name, 0)):
                if self._at_total_cap():
                    break
                launched.append(self._launch(type_name))

        # 3. Unmet demand -> more nodes (simple first-fit-decreasing binpack
        # onto hypothetical new nodes, the v2 scheduler.py role).
        demand = list(self.scheduler.pending_demand())
        for bundles in self.scheduler.pending_pg_demand():
            demand.extend(bundles)
        for type_name, n in self._binpack(demand).items():
            cfg = self.config.node_types[type_name]
            counts = self._counts()
            room = cfg.max_workers - counts.get(type_name, 0)
            if self.config.max_total_workers is not None:
                # Cluster-wide cap binds across all types together.
                room = min(room, self.config.max_total_workers
                           - sum(counts.values()))
            for _ in range(min(n, room,
                               self.config.max_launches_per_round - len(launched))):
                launched.append(self._launch(type_name))

        # 4. Idle nodes past timeout -> terminate (never below min_workers,
        # never a node with resources in use).
        now = time.time()
        counts = self._counts()
        with self._lock:
            owned = dict(self._owned)
        for pid, type_name in owned.items():
            cfg = self.config.node_types.get(type_name)
            if cfg is None or counts.get(type_name, 0) <= cfg.min_workers:
                continue
            node = self._scheduler_node(pid)
            if node is None:
                continue
            busy = any(node.available.get(k, 0.0) < v
                       for k, v in node.total.items())
            if not busy and now - node.last_busy > self.config.idle_timeout_s:
                self.provider.terminate_node(pid)
                with self._lock:
                    self._owned.pop(pid, None)
                counts[type_name] -= 1
                terminated.append(pid)
        return {"launched": launched, "terminated": terminated}

    # -------------------------------------------------------------- helpers
    def _at_total_cap(self) -> bool:
        cap = self.config.max_total_workers
        if cap is None:
            return False
        with self._lock:
            return len(self._owned) >= cap

    def _launch(self, type_name: str) -> str:
        cfg = self.config.node_types[type_name]
        pid = self.provider.create_node(type_name, dict(cfg.resources),
                                        dict(cfg.labels))
        with self._lock:
            self._owned[pid] = type_name
        return pid

    def _counts(self) -> Dict[str, int]:
        with self._lock:
            counts: Dict[str, int] = {}
            for type_name in self._owned.values():
                counts[type_name] = counts.get(type_name, 0) + 1
            return counts

    def _scheduler_node(self, pid: str):
        node_id = getattr(self.provider, "scheduler_node_id", lambda _: None)(pid)
        if node_id is None:
            return None
        return self.scheduler.get_node(node_id)

    def _binpack(self, demand: List[Resources]) -> Dict[str, int]:
        """How many nodes of each type cover `demand` (first-fit decreasing;
        existing free capacity is NOT counted — demand is what's blocked
        *after* the scheduler already tried to place it)."""
        if not demand:
            return {}
        demand = sorted(demand,
                        key=lambda r: -sum(v for v in r.values()))
        bins: List[tuple] = []  # (type_name, remaining)
        need: Dict[str, int] = {}
        for req in demand:
            placed = False
            for type_name, remaining in bins:
                if all(remaining.get(k, 0.0) >= v for k, v in req.items()):
                    for k, v in req.items():
                        remaining[k] = remaining.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            # Open a new bin of the cheapest feasible type.
            for type_name, cfg in self.config.node_types.items():
                if all(cfg.resources.get(k, 0.0) >= v for k, v in req.items()):
                    remaining = dict(cfg.resources)
                    for k, v in req.items():
                        remaining[k] -= v
                    bins.append((type_name, remaining))
                    need[type_name] = need.get(type_name, 0) + 1
                    break
            # No feasible type: skip — the scheduler's feasibility check
            # already counts autoscaler_node_shapes, so such a request
            # raised InfeasibleError at submit instead of reaching here.
        return need


class Monitor:
    """Background reconcile loop (ref: _private/monitor.py Monitor:127)."""

    def __init__(self, autoscaler: Autoscaler, interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="ray_tpu_autoscaler", daemon=True)

    def start(self) -> "Monitor":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:  # reconcile must survive transient errors
                import traceback

                traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self.autoscaler.scheduler.autoscaling_enabled = False
        self.autoscaler.scheduler.autoscaler_node_shapes = []
