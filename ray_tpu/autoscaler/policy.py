"""Signal-driven cluster autoscaling policy (node tier).

The deciding half of the cluster control loop (signals.py senses,
``Autoscaler`` actuates): :class:`ClusterAutoscaler` wraps the
reconciler-shaped :class:`~ray_tpu.autoscaler.autoscaler.Autoscaler` and
composes per-node-type node-count targets from one
:class:`~ray_tpu.autoscaler.signals.ClusterSignals` snapshot — the PR 18
replica-tier policy pattern lifted to nodes (ref: the reference's
monitor.py + resource_demand_scheduler load-metrics path):

- **serve-driven** (non-preemptible "protected" types): windowed request
  rate vs ``serve_qps_per_node``, router in-flight depth vs
  ``serve_inflight_per_node``; SLO burn alerting multiplies the target
  and bypasses the upscale hysteresis delay (never the cooldown).
- **train-driven** (``preemptible`` types — cheap capacity the elastic
  controller already survives losing, PR 6): data-starved fraction over
  threshold adds a node; pending ingest shards vs ``shards_per_node``
  sizes the reader fleet.
- **static demand floor**: blocked resource requests keep flowing
  through the wrapped autoscaler's bin-packing unchanged — the policy
  only ever raises targets above that floor or releases *idle* capacity
  back down to it.

Per-type asymmetric hysteresis (``upscale_delay_s`` /
``downscale_delay_s``) and per-direction cooldowns; scale-down steps one
node per decision.  All state is keyed on the signal snapshot's ``now``
so the layer is deterministic under test.

**Postmortem health gate**: node-attributed crash/stall postmortems from
the forensics stream feed a :class:`QuarantineTracker`; a node that
produces ``quarantine_postmortems`` of them inside
``quarantine_window_s`` is quarantined — drained in the scheduler
(excluded from placement), its instance terminated, and its node type's
worker caps permanently shrunk by one so the slot is never refilled —
instead of being relaunched into the same crash loop.

The ``cluster_autoscale`` fault point is consulted BEFORE every
actuation (target change or quarantine): an injected decision failure
leaves the cluster untouched.  Every applied change is recorded as
``ray_tpu_cluster_*`` metrics plus a flight-recorder row, under a
``cluster.autoscale`` span per tick.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import fault_injection
from ray_tpu.autoscaler import metrics as _metrics
from ray_tpu.autoscaler.autoscaler import Autoscaler
from ray_tpu.autoscaler.signals import (ClusterSignals, SignalCollector)
from ray_tpu.util import tracing


@dataclass
class ClusterPolicyConfig:
    """Knobs for the signal-composed policy (per cluster, applied to every
    node type; signal→type routing comes from ``NodeTypeConfig.preemptible``).
    A per-node capacity knob left at 0 disables that signal."""

    #: Serve request rate one protected node is expected to absorb.
    serve_qps_per_node: float = 0.0
    #: Mean router in-flight depth one protected node is expected to hold.
    serve_inflight_per_node: float = 0.0
    #: SLO burn multiplies the protected target by this (and bypasses the
    #: upscale hysteresis delay, never the cooldown).
    burn_upscale_factor: float = 1.5
    #: Data-starved fraction above this adds one preemptible node.
    starved_fraction_threshold: float = 0.25
    #: Pending ingest shards one preemptible node is expected to drain.
    shards_per_node: float = 0.0
    upscale_delay_s: float = 5.0
    downscale_delay_s: float = 60.0
    upscale_cooldown_s: float = 10.0
    downscale_cooldown_s: float = 60.0
    #: Health gate: this many node-attributed crash/stall postmortems
    #: inside the window quarantines the node.
    quarantine_postmortems: int = 3
    quarantine_window_s: float = 600.0
    #: Trailing window the signal collector queries.
    signal_window_s: float = 60.0


@dataclass
class Decision:
    node_type: str
    target: int
    reason: str
    changed: bool


class _TypeState:
    """Per-node-type hysteresis/cooldown state (the serve
    DeploymentAutoscaler state machine, one per node type)."""

    def __init__(self) -> None:
        self.above_since = -1.0
        self.below_since = -1.0
        self.last_up_at = -math.inf
        self.last_down_at = -math.inf


class QuarantineTracker:
    """Counts node-attributed health postmortems and decides quarantine.

    Dump files are keyed ``{pid}-{reason}.json`` so a crash-looping
    process OVERWRITES its own dump — a known id reappearing with a newer
    ``ts`` is a fresh postmortem, which is why events are tracked as
    (id, ts) pairs rather than ids."""

    def __init__(self, threshold: int = 3, window_s: float = 600.0):
        self.threshold = max(1, int(threshold))
        self.window_s = window_s
        #: node -> [(dump_id, ts)] health events seen (window-pruned).
        self._events: Dict[str, List[Tuple[str, float]]] = {}
        self._last_ts: Dict[str, float] = {}  # dump id -> last seen ts
        self.quarantined: Dict[str, str] = {}  # node -> tipping reason

    def observe(self, postmortems: List[Dict[str, Any]],
                now: float) -> List[Tuple[str, str]]:
        """Fold one batch of forensics rows in; returns newly quarantined
        ``(node, reason)`` pairs."""
        new: List[Tuple[str, str]] = []
        for row in postmortems:
            ts = float(row.get("ts") or 0.0)
            dump_id = str(row["id"])
            if self._last_ts.get(dump_id) == ts:
                continue  # same dump observed again, not a new event
            self._last_ts[dump_id] = ts
            node = str(row["node"])
            _metrics.POSTMORTEMS_SEEN.inc(1)
            self._events.setdefault(node, []).append((dump_id, ts))
            if node in self.quarantined:
                continue
            events = [e for e in self._events[node]
                      if now - e[1] <= self.window_s]
            self._events[node] = events
            if len(events) >= self.threshold:
                reason = str(row.get("reason") or "unknown")
                self.quarantined[node] = reason
                new.append((node, reason))
        _metrics.QUARANTINED_NODES.set(len(self.quarantined))
        return new


class ClusterAutoscaler:
    """Signal-composed node-count targets + postmortem quarantine around a
    wrapped :class:`Autoscaler`.  ``tick()`` is the whole loop: collect →
    health gate → per-type decide → fault-gated actuate → reconcile."""

    def __init__(self, autoscaler: Autoscaler,
                 policy: Optional[ClusterPolicyConfig] = None,
                 collector: Optional[SignalCollector] = None):
        self.autoscaler = autoscaler
        self.policy = policy or ClusterPolicyConfig()
        self.collector = collector or SignalCollector(
            scheduler=autoscaler.scheduler,
            window_s=self.policy.signal_window_s)
        self.quarantine = QuarantineTracker(
            self.policy.quarantine_postmortems,
            self.policy.quarantine_window_s)
        self._state: Dict[str, _TypeState] = {
            t: _TypeState() for t in autoscaler.config.node_types}
        self.last_decisions: List[Decision] = []

    # ------------------------------------------------------------- policies
    def _signal_desired(self, node_type: str, sig: ClusterSignals,
                        active: int) -> Tuple[int, str]:
        """(desired node count, driving reason) for one type from the
        windowed signals alone — the static-demand floor stays with the
        wrapped autoscaler's binpack."""
        cfg = self.autoscaler.config.node_types[node_type]
        pol = self.policy
        desired, reason = 0, "steady"
        if getattr(cfg, "preemptible", False):
            # Train-driven: cheap capacity for elastic training readers.
            if pol.shards_per_node > 0 and sig.pending_ingest_shards > 0:
                d = math.ceil(sig.pending_ingest_shards / pol.shards_per_node)
                if d > desired:
                    desired, reason = d, "pending_shards"
            if sig.train_data_starved_fraction \
                    >= pol.starved_fraction_threshold:
                d = active + 1
                if d > desired:
                    desired, reason = d, "data_starved"
        else:
            # Serve-driven: protected capacity, never preempted for cost.
            if pol.serve_qps_per_node > 0:
                d = math.ceil(sig.serve_request_rate / pol.serve_qps_per_node)
                if d > desired:
                    desired, reason = d, "request_rate"
            if pol.serve_inflight_per_node > 0:
                d = math.ceil(sig.serve_inflight / pol.serve_inflight_per_node)
                if d > desired:
                    desired, reason = d, "queue_depth"
            if sig.slo_burn_alerting:
                d = max(active + 1,
                        math.ceil(active * pol.burn_upscale_factor))
                if d > desired:
                    desired, reason = d, "slo_burn"
        desired = min(max(desired, cfg.min_workers), cfg.max_workers)
        return desired, reason

    def _decide(self, node_type: str, sig: ClusterSignals,
                active: int, target: int) -> Decision:
        pol, st, now = self.policy, self._state[node_type], sig.now
        desired, reason = self._signal_desired(node_type, sig, active)
        if desired > target:
            st.below_since = -1.0
            if st.above_since < 0:
                st.above_since = now
            # Burn bypasses the hysteresis delay, never the cooldown.
            ready = (reason == "slo_burn"
                     or now - st.above_since >= pol.upscale_delay_s)
            if ready and now - st.last_up_at >= pol.upscale_cooldown_s:
                st.above_since = -1.0
                st.last_up_at = now
                return Decision(node_type, desired, reason, True)
            return Decision(node_type, target, f"pending_up:{reason}", False)
        st.above_since = -1.0
        if desired < target:
            if not sig.slo_burn_quiet and not getattr(
                    self.autoscaler.config.node_types[node_type],
                    "preemptible", False):
                # Protected capacity only comes down once every SLO
                # window of every objective is quiet.
                st.below_since = -1.0
                return Decision(node_type, target, "hold_burn_not_quiet",
                                False)
            if st.below_since < 0:
                st.below_since = now
            # Step down one node per decision: releases are cheap to
            # repeat, mass shrinks race the elastic controller's redeploy.
            new = max(target - 1, desired)
            if now - st.below_since >= pol.downscale_delay_s \
                    and now - st.last_down_at >= pol.downscale_cooldown_s:
                st.below_since = -1.0
                st.last_down_at = now
                return Decision(node_type, new, "scale_down", True)
            return Decision(node_type, target, "pending_down", False)
        st.below_since = -1.0
        return Decision(node_type, target, "steady", False)

    # ---------------------------------------------------------- quarantine
    def _quarantine_node(self, node: str, reason: str) -> None:
        """Drain, terminate, and permanently retire one node's slot."""
        from ray_tpu.autoscaler.instance_manager import InstanceState

        sched = self.autoscaler.scheduler
        if hasattr(sched, "set_node_draining"):
            sched.set_node_draining(node, True)
        inst = next(
            (i for i in self.autoscaler.im.instances(
                InstanceState.RUNNING, InstanceState.ALLOCATED)
             if str(i.scheduler_node_id) == node), None)
        if inst is not None:
            self.autoscaler.im.transition(
                inst, InstanceState.TERMINATING,
                f"quarantined: {reason}")
            cfg = self.autoscaler.config.node_types.get(inst.node_type)
            if cfg is not None:
                # Never refilled: the slot leaves the type's caps for
                # good — relaunching into the same crash loop is the
                # failure mode this gate exists to break.
                cfg.max_workers = max(0, cfg.max_workers - 1)
                cfg.min_workers = min(cfg.min_workers, cfg.max_workers)
                tc = self.autoscaler.target_counts
                if inst.node_type in tc:
                    tc[inst.node_type] = min(tc[inst.node_type],
                                             cfg.max_workers)
        _metrics.QUARANTINES.inc(1, tags={"reason": reason})
        from ray_tpu.util import flight_recorder
        flight_recorder.record_event(
            "cluster.quarantine",
            {"node": node, "reason": reason,
             "node_type": inst.node_type if inst else None},
            kind="autoscale")

    # ----------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None,
             signals: Optional[ClusterSignals] = None) -> dict:
        """One control pass: sense, gate health, decide, actuate,
        reconcile.  Returns the wrapped autoscaler's reconcile summary
        plus this layer's decisions."""
        with tracing.span("cluster.autoscale"):
            sig = signals if signals is not None \
                else self.collector.collect(now=now)
            return self._tick(sig)

    def _tick(self, sig: ClusterSignals) -> dict:
        decisions: List[Decision] = []
        quarantined: List[str] = []
        # 1. Health gate first: a node being quarantined this pass must
        # not be counted as healthy capacity by the decisions below.
        for node, reason in self.quarantine.observe(sig.postmortems,
                                                    sig.now):
            try:
                fault_injection.check("cluster_autoscale")
            except Exception:  # noqa: BLE001 — injected: leave untouched
                self.quarantine.quarantined.pop(node, None)
                _metrics.DECISIONS.inc(1, tags={"node_type": "-",
                                                "reason": "fault_injected"})
                continue
            self._quarantine_node(node, reason)
            quarantined.append(node)
        # 2. Per-type signal policy.
        counts = self.autoscaler.im.active_counts()
        for node_type in self.autoscaler.config.node_types:
            self._state.setdefault(node_type, _TypeState())
            active = counts.get(node_type, 0)
            target = self.autoscaler.target_counts.get(node_type, active)
            decision = self._decide(node_type, sig, active, target)
            decisions.append(decision)
            _metrics.ACTIVE_NODES.set(active, tags={"node_type": node_type})
            if not decision.changed:
                _metrics.DECISIONS.inc(1, tags={"node_type": node_type,
                                                "reason": decision.reason})
                continue
            try:
                fault_injection.check("cluster_autoscale")
            except Exception:  # noqa: BLE001 — injected: target unchanged
                _metrics.DECISIONS.inc(1, tags={"node_type": node_type,
                                                "reason": "fault_injected"})
                continue
            self._apply(node_type, target, decision)
        self.last_decisions = decisions
        # 3. Reconcile: the wrapped autoscaler launches/terminates toward
        # the new targets (plus its own static-demand floor) in the same
        # pass, so a tick is sense->act, not sense->wait-for-monitor.
        result = self.autoscaler.update()
        result["decisions"] = [(d.node_type, d.target, d.reason)
                               for d in decisions if d.changed]
        result["quarantined"] = quarantined
        return result

    def _apply(self, node_type: str, old: int, decision: Decision) -> None:
        self.autoscaler.target_counts[node_type] = decision.target
        _metrics.DECISIONS.inc(1, tags={"node_type": node_type,
                                        "reason": decision.reason})
        if decision.target > old:
            _metrics.SCALE_UP.inc(1, tags={"node_type": node_type})
        else:
            _metrics.SCALE_DOWN.inc(1, tags={"node_type": node_type})
        _metrics.TARGET_NODES.set(decision.target,
                                  tags={"node_type": node_type})
        from ray_tpu.util import flight_recorder
        flight_recorder.record_event(
            "cluster.autoscale",
            {"node_type": node_type, "from": old, "to": decision.target,
             "reason": decision.reason},
            kind="autoscale")
