"""Instance manager: the autoscaler v2 per-instance FSM + persisted storage.

Counterpart of the reference's v2 instance manager (ref:
python/ray/autoscaler/v2/instance_manager/reconciler.py:53 Reconciler,
instance_storage.py, instance_manager.py): every node the autoscaler ever
requested is an Instance with an explicit lifecycle

    REQUESTED -> ALLOCATED -> RUNNING -> TERMINATING -> TERMINATED
         \\-> ALLOCATION_FAILED          RUNNING -> FAILED (died under us)

a per-instance failure log, and durable storage (JSON snapshot in the
session dir, atomic replace) so a restarted autoscaler reconciles against
what it already owns instead of double-launching.  The reconciler compares
three views every pass — the instance table (intent), the provider's live
nodes (cloud truth), and the scheduler's node states (cluster truth) — and
drives each instance toward its goal state; observed drift (a provider node
vanishing under a RUNNING instance) transitions the instance to FAILED,
which frees its slot so demand/min_workers relaunch a replacement.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional


class InstanceState:
    REQUESTED = "REQUESTED"
    ALLOCATED = "ALLOCATED"
    RUNNING = "RUNNING"
    TERMINATING = "TERMINATING"
    TERMINATED = "TERMINATED"
    ALLOCATION_FAILED = "ALLOCATION_FAILED"
    FAILED = "FAILED"


#: Legal transitions (ref: reconciler.py's state machine, reduced to the
#: states this runtime distinguishes).
_TRANSITIONS = {
    InstanceState.REQUESTED: {InstanceState.ALLOCATED,
                              InstanceState.ALLOCATION_FAILED},
    InstanceState.ALLOCATED: {InstanceState.RUNNING,
                              InstanceState.TERMINATING,
                              InstanceState.FAILED},
    InstanceState.RUNNING: {InstanceState.TERMINATING, InstanceState.FAILED},
    InstanceState.TERMINATING: {InstanceState.TERMINATED,
                                InstanceState.FAILED},
    InstanceState.TERMINATED: set(),
    InstanceState.ALLOCATION_FAILED: set(),
    InstanceState.FAILED: set(),
}

#: States that still occupy a cluster slot (count against caps/min_workers).
ACTIVE_STATES = frozenset({InstanceState.REQUESTED, InstanceState.ALLOCATED,
                           InstanceState.RUNNING})
TERMINAL_STATES = frozenset({InstanceState.TERMINATED,
                             InstanceState.ALLOCATION_FAILED,
                             InstanceState.FAILED})


@dataclass
class Instance:
    instance_id: str
    node_type: str
    state: str = InstanceState.REQUESTED
    provider_node_id: Optional[str] = None
    scheduler_node_id: Optional[str] = None
    created_at: float = field(default_factory=time.time)
    #: [(state, unix_time, message)] — the per-instance audit/failure log.
    history: List[List] = field(default_factory=list)
    launch_attempt: int = 1

    def transition(self, new_state: str, message: str = "") -> None:
        if new_state not in _TRANSITIONS.get(self.state, set()):
            raise ValueError(
                f"instance {self.instance_id}: illegal transition "
                f"{self.state} -> {new_state}")
        self.state = new_state
        self.history.append([new_state, time.time(), message])


class InstanceStorage:
    """Durable instance table: one JSON snapshot, atomic replace on every
    mutation batch (the instance_storage.py role; a snapshot rather than a
    WAL because the table is small and the write is one syscall)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._instances: Dict[str, Instance] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    raw = json.load(f)
                for d in raw:
                    self._instances[d["instance_id"]] = Instance(**d)
            except (OSError, ValueError, KeyError, TypeError):
                pass  # corrupt snapshot: start empty (provider is truth)

    def upsert(self, *instances: Instance) -> None:
        for inst in instances:
            self._instances[inst.instance_id] = inst
        self._flush()

    def get(self, instance_id: str) -> Optional[Instance]:
        return self._instances.get(instance_id)

    def all(self) -> List[Instance]:
        return list(self._instances.values())

    def prune_terminal(self, keep: int = 64) -> None:
        """Bound the table: keep only the newest `keep` terminal records."""
        terminal = sorted(
            (i for i in self._instances.values() if i.state in TERMINAL_STATES),
            key=lambda i: i.created_at)
        doomed = terminal[:-keep] if keep else terminal
        if not doomed:
            return  # nothing changed: skip the snapshot rewrite
        for inst in doomed:
            del self._instances[inst.instance_id]
        self._flush()

    def _flush(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump([asdict(i) for i in self._instances.values()], f)
        os.replace(tmp, self.path)


class InstanceManager:
    """Owns the instance table and the FSM transitions; the Autoscaler's
    reconcile pass is written against this, not raw provider ids."""

    def __init__(self, storage: InstanceStorage):
        self.storage = storage
        self._lock = threading.Lock()

    # ------------------------------------------------------------ mutation
    def request(self, node_type: str) -> Instance:
        inst = Instance(instance_id=f"inst-{uuid.uuid4().hex[:10]}",
                        node_type=node_type)
        inst.history.append([inst.state, time.time(), "requested"])
        with self._lock:
            self.storage.upsert(inst)
        return inst

    def transition(self, inst: Instance, state: str, message: str = "",
                   **fields) -> None:
        with self._lock:
            inst.transition(state, message)
            for k, v in fields.items():
                setattr(inst, k, v)
            self.storage.upsert(inst)

    # ------------------------------------------------------------- queries
    def instances(self, *states: str) -> List[Instance]:
        with self._lock:
            if not states:
                return self.storage.all()
            wanted = set(states)
            return [i for i in self.storage.all() if i.state in wanted]

    def active_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for inst in self.instances(*ACTIVE_STATES):
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
        return counts

    # ----------------------------------------------------------- reconcile
    def reconcile_drift(self, provider_live: set, scheduler) -> List[Instance]:
        """Compare intent vs cloud truth vs cluster truth; returns the
        instances newly marked FAILED (the caller's signal to replace)."""
        failed = []
        for inst in self.instances(InstanceState.ALLOCATED,
                                   InstanceState.RUNNING):
            if inst.provider_node_id not in provider_live:
                self.transition(
                    inst, InstanceState.FAILED,
                    "provider node vanished (killed / preempted)")
                failed.append(inst)
                continue
            if inst.state == InstanceState.RUNNING and scheduler is not None \
                    and inst.scheduler_node_id is not None:
                node = scheduler.get_node(inst.scheduler_node_id)
                if node is not None and not node.alive:
                    self.transition(
                        inst, InstanceState.FAILED,
                        "scheduler marked the node dead")
                    failed.append(inst)
        # TERMINATING instances whose provider node is already gone landed.
        for inst in self.instances(InstanceState.TERMINATING):
            if inst.provider_node_id not in provider_live:
                self.transition(inst, InstanceState.TERMINATED, "")
        return failed
