"""Cluster launcher: `ray_tpu up/down` over a cluster YAML.

(ref: python/ray/autoscaler/_private/commands.py — `ray up` reads a cluster
YAML validated against ray-schema.json, instantiates the configured
NodeProvider, creates the head node, then lets the autoscaler reconcile
worker counts between min_workers and max_workers.)

TPU-native shape: providers provision *scheduler nodes* (virtual hosts for
the in-process control plane, or TPU pod slices via TPUPodProvider), so
`up` = init the runtime as head + create min workers + start the
reconciling Monitor.  Cloud VMs are out of scope offline; the provider
interface is where AWS/GCP/K8s plugins slot in (``provider.type`` accepts a
"module:Class" import path exactly for that).
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import (Autoscaler, AutoscalerConfig,
                                           Monitor, NodeTypeConfig)
from ray_tpu.autoscaler.gce import GCETPUNodeProvider
from ray_tpu.autoscaler.node_provider import (FakeNodeProvider, NodeProvider,
                                              SubprocessNodeProvider,
                                              TPUPodProvider)

_BUILTIN_PROVIDERS = {
    "fake": FakeNodeProvider,
    "local": FakeNodeProvider,
    "tpu_pod": TPUPodProvider,
    # Real worker-node processes joined over the node protocol — the
    # loopback analogue of the SSH command_runner bootstrap.
    "subprocess": SubprocessNodeProvider,
    # Real worker-node processes behind a (mockable) GCE TPU API client
    # (ref: autoscaler/_private/gcp/node_provider.py).
    "gce_tpu": GCETPUNodeProvider,
}


class ClusterConfigError(ValueError):
    """Schema violation in the cluster YAML (ref: ray-schema.json checks)."""


@dataclass
class ClusterConfig:
    cluster_name: str
    provider: NodeProvider
    node_types: Dict[str, NodeTypeConfig]
    head_node_type: str
    #: cluster-wide worker cap; None (top-level key absent) = unbounded.
    max_workers: Optional[int] = None
    idle_timeout_s: float = 60.0
    head_resources: Dict[str, float] = field(default_factory=dict)


def load_cluster_config(source: Any) -> ClusterConfig:
    """Parse + validate a cluster YAML path, YAML string, or dict."""
    if isinstance(source, dict):
        raw = source
    else:
        import os

        import yaml

        s = str(source)
        # Inline YAML (flow style included) contains ':' or '{'; paths don't.
        looks_like_path = "\n" not in s and ":" not in s and "{" not in s \
            and (s.endswith((".yaml", ".yml")) or os.sep in s)
        if os.path.exists(s):
            with open(s) as f:
                text = f.read()
        elif looks_like_path:
            # A typo'd filename must not be parsed AS yaml — that yields a
            # baffling "must be a mapping" error instead of the real cause.
            raise FileNotFoundError(f"cluster config not found: {s}")
        else:
            text = s  # inline YAML string
        raw = yaml.safe_load(text)
    if not isinstance(raw, dict):
        raise ClusterConfigError("cluster config must be a mapping")

    name = raw.get("cluster_name", "default")
    provider_cfg = raw.get("provider") or {}
    ptype = provider_cfg.get("type", "fake")
    provider_cls = _BUILTIN_PROVIDERS.get(ptype)
    if provider_cls is None:
        if ":" not in ptype:
            raise ClusterConfigError(
                f"unknown provider type {ptype!r}; builtins: "
                f"{sorted(_BUILTIN_PROVIDERS)} or 'module:Class'")
        mod, _, cls = ptype.partition(":")
        provider_cls = getattr(importlib.import_module(mod), cls)
    kwargs = {k: v for k, v in provider_cfg.items() if k != "type"}
    provider = provider_cls(**kwargs)

    types_raw = raw.get("available_node_types")
    if not types_raw:
        raise ClusterConfigError("available_node_types must list >=1 type")
    node_types: Dict[str, NodeTypeConfig] = {}
    for tname, tcfg in types_raw.items():
        if "resources" not in tcfg:
            raise ClusterConfigError(f"node type {tname!r} needs resources")
        node_types[tname] = NodeTypeConfig(
            resources={k: float(v) for k, v in tcfg["resources"].items()},
            min_workers=int(tcfg.get("min_workers", 0)),
            max_workers=int(tcfg.get("max_workers",
                                     raw.get("max_workers", 10))),
            labels=dict(tcfg.get("labels", {})))

    head_type = raw.get("head_node_type")
    if head_type is None or head_type not in node_types:
        raise ClusterConfigError(
            f"head_node_type {head_type!r} must name an available_node_type")
    top_max = raw.get("max_workers")
    return ClusterConfig(
        cluster_name=name, provider=provider, node_types=node_types,
        head_node_type=head_type,
        max_workers=None if top_max is None else int(top_max),
        idle_timeout_s=float(raw.get("idle_timeout_s", 60.0)),
        head_resources=dict(node_types[head_type].resources))


class ClusterHandle:
    """A launched cluster (ref: the state `ray up` leaves behind)."""

    def __init__(self, config: ClusterConfig, autoscaler: Autoscaler,
                 monitor: Optional[Monitor], worker_ids: List[str]):
        self.config = config
        self.autoscaler = autoscaler
        self.monitor = monitor
        self.worker_ids = list(worker_ids)

    def status(self) -> Dict[str, Any]:
        import ray_tpu

        return {
            "cluster_name": self.config.cluster_name,
            "nodes": len(ray_tpu.nodes()),
            "workers": len(self.config.provider.non_terminated_nodes()),
            "resources": ray_tpu.cluster_resources(),
        }

    def teardown(self) -> None:
        """`ray down`: stop reconciling, terminate workers, shut the head."""
        import ray_tpu

        if self.monitor is not None:
            self.monitor.stop()
        for pid in list(self.config.provider.non_terminated_nodes()):
            self.config.provider.terminate_node(pid)
        ray_tpu.shutdown()


def launch_cluster(source: Any, *, autoscale: bool = True) -> ClusterHandle:
    """`ray up`: head + min_workers per type (+ reconciler when autoscale).

    Idempotent-ish like the reference: re-running against a live runtime
    reuses it (`ignore_reinit_error`).
    """
    import ray_tpu

    config = load_cluster_config(source)
    ray_tpu.init(ignore_reinit_error=True, resources=config.head_resources)
    as_config = AutoscalerConfig(node_types=config.node_types,
                                 idle_timeout_s=config.idle_timeout_s,
                                 max_total_workers=config.max_workers,
                                 cluster_name=config.cluster_name)
    autoscaler = Autoscaler(as_config, config.provider)
    worker_ids: List[str] = []
    for tname, tcfg in config.node_types.items():
        for _ in range(tcfg.min_workers):
            if autoscaler._at_total_cap():
                break
            pid = autoscaler._launch(tname)
            if pid:
                worker_ids.append(pid)
    monitor = Monitor(autoscaler).start() if autoscale else None
    return ClusterHandle(config, autoscaler, monitor, worker_ids)


EXAMPLE_YAML = """\
cluster_name: tpu-pod
max_workers: 8
provider:
  type: tpu_pod
  accelerator: v5e
  chips_per_host: 4
head_node_type: cpu_head
available_node_types:
  cpu_head:
    resources: {CPU: 8}
    min_workers: 0
  tpu_worker:
    resources: {CPU: 4, TPU: 4}
    min_workers: 2
    max_workers: 8
"""
