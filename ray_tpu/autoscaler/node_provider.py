"""Node providers: the pluggable cloud interface of the autoscaler.

Counterpart of the reference's `NodeProvider` plugin family (ref:
python/ray/autoscaler/node_provider.py + _private/fake_multi_node/
node_provider.py): the reconciler talks to this interface only, so cloud
specifics (GCE TPU pods, fake in-process nodes for tests) stay behind it.

TPU twist: `TPUPodProvider` allocates whole ICI slices — a "node" is a TPU
host with its chips, labeled with its slice so the scheduler's slice-affinity
packing (scheduling.py ici-slice label) keeps collective-heavy work on one
ICI domain, the analogue of the reference's `TPU-<ver>-<chips>-head` resource
(_private/accelerators/tpu.py:356).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal surface the reconciler needs (ref: node_provider.py)."""

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        """Terminate one node.  MUST be idempotent: terminating an
        already-terminated (or never-seen) id is a no-op, never a
        KeyError — the quarantine path and the reconciler's leaked-node
        sweep can race to terminate the same node, and the loser of that
        race must not crash the reconcile pass."""
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes virtual scheduler nodes in the running runtime — the
    in-process analogue of the reference's fake multi-node provider, which is
    how autoscaler logic is tested without a cloud."""

    def __init__(self, launch_delay_s: float = 0.0):
        self._nodes: Dict[str, object] = {}  # provider id -> scheduler NodeID
        self._lock = threading.Lock()
        self.launch_delay_s = launch_delay_s

    def create_node(self, node_type, resources, labels) -> str:
        from ray_tpu._private.runtime import get_runtime

        if self.launch_delay_s:
            time.sleep(self.launch_delay_s)
        node_id = get_runtime().scheduler.add_node(
            dict(resources), {**labels, "node-type": node_type})
        pid = f"fake-{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._nodes[pid] = node_id
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        from ray_tpu._private.runtime import get_runtime

        with self._lock:
            node_id = self._nodes.pop(provider_node_id, None)
        if node_id is not None:
            get_runtime().scheduler.remove_node(node_id)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def scheduler_node_id(self, provider_node_id: str):
        with self._lock:
            return self._nodes.get(provider_node_id)


class SubprocessNodeProvider(NodeProvider):
    """Launches REAL worker-node processes on this host — the loopback
    analogue of the reference's SSH `command_runner` bootstrap (ref:
    autoscaler/_private/command_runner.py + commands.py `ray up`): the
    provider's "cloud API" is subprocess.Popen, its bootstrap command is
    the same `python -m ray_tpu worker --address ...` a remote SSH
    provider would run, and the launched node JOINS the head over the node
    protocol exactly like a cross-host worker.  `up/down` against this
    provider exercises live nodes, not mocks."""

    def __init__(self, address: Optional[str] = None):
        self._procs: Dict[str, object] = {}   # provider id -> Popen
        self._node_ids: Dict[str, object] = {}  # provider id -> NodeID
        self._lock = threading.Lock()
        self._address = address

    def _head_address(self) -> str:
        if self._address:
            return self._address
        from ray_tpu._private.runtime import get_runtime

        self._address = get_runtime().start_node_server()
        return self._address

    def create_node(self, node_type, resources, labels) -> str:
        import subprocess

        from ray_tpu._private.ids import NodeID
        from ray_tpu.cluster_utils import worker_node_cmd, worker_node_env

        node_id = NodeID.from_random()
        res = dict(resources)
        cpus = res.pop("CPU", 1.0)
        cmd = worker_node_cmd(self._head_address(), cpus, res,
                              {**labels, "node-type": node_type},
                              str(node_id))
        proc = subprocess.Popen(cmd, env=worker_node_env(),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        pid = f"proc-{proc.pid}"
        with self._lock:
            self._procs[pid] = proc
            self._node_ids[pid] = node_id
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(provider_node_id, None)
            self._node_ids.pop(provider_node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            # Cloud truth is the OS process table: an externally killed
            # worker (chaos, OOM) is observed here, which is what lets the
            # reconciler mark its instance FAILED and replace it.  Dead
            # entries are reaped on observation (poll() already collected
            # the exit status) so a churning cluster doesn't accumulate
            # Popen handles nor re-poll every historical corpse.
            live = []
            for pid, proc in list(self._procs.items()):
                if proc.poll() is None:
                    live.append(pid)
                else:
                    del self._procs[pid]
                    self._node_ids.pop(pid, None)
            return live

    def scheduler_node_id(self, provider_node_id: str):
        with self._lock:
            return self._node_ids.get(provider_node_id)

    def shutdown(self) -> None:
        for pid in list(self._procs):
            self.terminate_node(pid)


class TPUPodProvider(FakeNodeProvider):
    """Slice-aware provider: every `hosts_per_slice` nodes created for a TPU
    node type share an ici-slice label, so STRICT_PACK placement groups land
    whole slices (the reference models this with TPU-pod head resources)."""

    def __init__(self, accelerator: str = "v5e", chips_per_host: int = 4,
                 hosts_per_slice: int = 4, launch_delay_s: float = 0.0):
        super().__init__(launch_delay_s)
        self.accelerator = accelerator
        self.chips_per_host = chips_per_host
        self.hosts_per_slice = hosts_per_slice
        self._slice_counter = 0
        self._in_slice = 0

    def create_node(self, node_type, resources, labels) -> str:
        with self._lock:
            if self._in_slice >= self.hosts_per_slice:
                self._slice_counter += 1
                self._in_slice = 0
            slice_name = f"{self.accelerator}-slice-{self._slice_counter}"
            first_in_slice = self._in_slice == 0
            self._in_slice += 1
        res = {**resources, "TPU": float(self.chips_per_host)}
        if first_in_slice:
            # Pod-head resource: one per slice, the scheduling anchor for
            # "give me the whole slice" (ref: tpu.py:356-358).
            size = self.chips_per_host * self.hosts_per_slice
            res[f"TPU-{self.accelerator}-{size}-head"] = 1.0
        return super().create_node(
            node_type, res,
            {**labels, "ici-slice": slice_name,
             "accelerator-type": f"tpu-{self.accelerator}"})
