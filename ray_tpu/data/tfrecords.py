"""TFRecord datasource — read/write tf.Example files without TensorFlow.

(ref: python/ray/data/read_api.py read_tfrecords + _internal/datasource/
tfrecords_datasource.py — the reference parses tf.train.Example protos out
of TFRecord framing.)  This image has neither tensorflow nor compiled
example protos, so both layers are implemented directly:

* TFRecord framing: ``u64le length | u32le masked-crc32c(length) | data |
  u32le masked-crc32c(data)`` with a table-driven CRC32-Castagnoli —
  files written here are readable by TensorFlow and vice versa.
* tf.train.Example: message classes built at import from the public
  schema (Example/Features/Feature/BytesList/FloatList/Int64List) with
  protobuf dynamic descriptors — wire-compatible with TF's.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

# --------------------------------------------------------------- crc32c
_CRC_TABLE: Optional[List[int]] = None


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c_py(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


try:
    # C extension when present (this image ships google_crc32c): a pure-
    # Python per-byte loop would bottleneck multi-GB record I/O.
    import google_crc32c as _gcrc

    def crc32c(data: bytes) -> int:
        return int(_gcrc.value(data))
except ImportError:  # pragma: no cover - exercised where the lib is absent
    crc32c = _crc32c_py


def _masked_crc(data: bytes) -> int:
    """TFRecord's masked CRC (ref: tensorflow record_writer.cc)."""
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# -------------------------------------------------------------- framing
def read_records(path: str, *, verify: bool = True) -> Iterable[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify and _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            footer = f.read(4)
            if len(data) < length or len(footer) < 4:
                raise ValueError(f"truncated TFRecord data in {path}")
            (data_crc,) = struct.unpack("<I", footer)
            if verify and _masked_crc(data) != data_crc:
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data


def read_records_range(path: str, start: int, end: int, *,
                       verify: bool = True) -> Iterable[bytes]:
    """Records whose HEADER offset lies in ``[start, end)`` — the
    offset-shard read unit (data/ingest/readers.py): disjoint byte ranges
    covering a file read disjoint, exactly-covering record sets, because a
    record belongs to whichever range holds its header byte (its data may
    extend past ``end``; that is fine, the next shard skips it while
    scanning for its own first boundary).

    TFRecord has no index, so a range starting mid-record resyncs by
    scanning forward one byte at a time until a candidate 12-byte header's
    masked length-crc verifies AND the record body's data-crc verifies —
    the double check makes a false sync on record payload bytes a ~2^-64
    event rather than a plausible one."""
    size = os.path.getsize(path)
    end = min(end, size)
    if start >= end:
        return
    with open(path, "rb") as f:
        pos = 0 if start == 0 else _next_frame_offset(f, start, end, size)
        if pos is None:
            return
        f.seek(pos)
        while pos < end:
            header = f.read(12)
            if len(header) < 12:
                if header and pos + len(header) < size:
                    raise ValueError(f"truncated TFRecord header in {path}")
                return
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify and _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            footer = f.read(4)
            if len(data) < length or len(footer) < 4:
                raise ValueError(f"truncated TFRecord data in {path}")
            (data_crc,) = struct.unpack("<I", footer)
            if verify and _masked_crc(data) != data_crc:
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data
            pos = f.tell()


def _next_frame_offset(f, start: int, limit: int,
                       size: int) -> Optional[int]:
    """First CRC-verified record-header offset in ``[start, limit)``, or
    None when the range holds no header (it was entirely inside a record
    owned by the previous shard).  The scan buffers the candidate range in
    one read — ranges are shard-sized (file/shards_per_file), i.e. already
    chosen to be memory-friendly."""
    f.seek(start)
    buf = f.read(limit - start + 12)
    span = len(buf) - 12
    for off in range(max(span, 0) + 1):
        if start + off >= limit:
            break
        header = buf[off:off + 12]
        if len(header) < 12:
            break
        (length,) = struct.unpack("<Q", header[:8])
        (len_crc,) = struct.unpack("<I", header[8:])
        if _masked_crc(header[:8]) != len_crc:
            continue
        body_end = start + off + 12 + length + 4
        if body_end > size:
            continue
        f.seek(start + off + 12)
        data = f.read(length)
        footer = f.read(4)
        if len(data) < length or len(footer) < 4:
            continue
        (data_crc,) = struct.unpack("<I", footer)
        if _masked_crc(data) == data_crc:
            return start + off
    return None


def write_records(path: str, records: Iterable[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


# ------------------------------------------------------------ tf.Example
_MSGS: Optional[Dict[str, Any]] = None


def example_messages() -> Dict[str, Any]:
    """tf.train message classes built from the public schema (the same
    dynamic-descriptor route the serve proto interop uses)."""
    global _MSGS
    if _MSGS is not None:
        return _MSGS
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    f = descriptor_pb2.FileDescriptorProto()
    f.name = "ray_tpu_tf_example.proto"
    f.package = "tensorflow"
    f.syntax = "proto3"
    FT = descriptor_pb2.FieldDescriptorProto

    bl = f.message_type.add()
    bl.name = "BytesList"
    fl = bl.field.add()
    fl.name, fl.number, fl.type, fl.label = "value", 1, FT.TYPE_BYTES, 3
    fll = f.message_type.add()
    fll.name = "FloatList"
    fl = fll.field.add()
    fl.name, fl.number, fl.type, fl.label = "value", 1, FT.TYPE_FLOAT, 3
    il = f.message_type.add()
    il.name = "Int64List"
    fl = il.field.add()
    fl.name, fl.number, fl.type, fl.label = "value", 1, FT.TYPE_INT64, 3

    feat = f.message_type.add()
    feat.name = "Feature"
    for fname, num, tname in (("bytes_list", 1, "BytesList"),
                              ("float_list", 2, "FloatList"),
                              ("int64_list", 3, "Int64List")):
        fl = feat.field.add()
        fl.name, fl.number, fl.label = fname, num, 1
        fl.type = FT.TYPE_MESSAGE
        fl.type_name = f".tensorflow.{tname}"
        fl.oneof_index = 0
    feat.oneof_decl.add().name = "kind"

    feats = f.message_type.add()
    feats.name = "Features"
    entry = feats.nested_type.add()  # map<string, Feature> wire form
    entry.name = "FeatureEntry"
    entry.options.map_entry = True
    k = entry.field.add()
    k.name, k.number, k.type, k.label = "key", 1, FT.TYPE_STRING, 1
    v = entry.field.add()
    v.name, v.number, v.label = "value", 2, 1
    v.type = FT.TYPE_MESSAGE
    v.type_name = ".tensorflow.Feature"
    fl = feats.field.add()
    fl.name, fl.number, fl.label = "feature", 1, 3
    fl.type = FT.TYPE_MESSAGE
    fl.type_name = ".tensorflow.Features.FeatureEntry"

    ex = f.message_type.add()
    ex.name = "Example"
    fl = ex.field.add()
    fl.name, fl.number, fl.label = "features", 1, 1
    fl.type = FT.TYPE_MESSAGE
    fl.type_name = ".tensorflow.Features"

    pool.Add(f)

    def cls(name):
        return message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"tensorflow.{name}"))

    _MSGS = {n: cls(n) for n in ("Example", "Features", "Feature",
                                 "BytesList", "FloatList", "Int64List")}
    return _MSGS


def example_to_row(data: bytes) -> Dict[str, Any]:
    ex = example_messages()["Example"].FromString(data)
    row: Dict[str, Any] = {}
    for key, feature in ex.features.feature.items():
        kind = feature.WhichOneof("kind")
        if kind == "bytes_list":
            vals: List[Any] = list(feature.bytes_list.value)
        elif kind == "float_list":
            vals = list(feature.float_list.value)
        elif kind == "int64_list":
            vals = list(feature.int64_list.value)
        else:
            vals = []
        # Scalar unwrap, like the reference's datasource.
        row[key] = vals[0] if len(vals) == 1 else vals
    return row


def examples_to_block(records: Iterable[bytes]):
    """Parsed examples -> an arrow block.  Columns where any example holds
    a multi-valued (or absent) feature become LIST columns — variable-
    length features are standard TFRecord usage and must not be funneled
    through a ragged np.asarray (which raises)."""
    import pyarrow as pa

    rows = [example_to_row(rec) for rec in records]
    if not rows:
        from ray_tpu.data.block import block_from_rows

        return block_from_rows([])
    keys = sorted({k for r in rows for k in r})
    arrays, names = [], []
    for key in keys:
        vals = [r.get(key) for r in rows]
        listy = any(isinstance(v, list) for v in vals)
        if listy:
            vals = [v if isinstance(v, list)
                    else ([] if v is None else [v]) for v in vals]
        arrays.append(pa.array(vals))
        names.append(key)
    return pa.table(arrays, names=names)


def row_to_example(row: Dict[str, Any]) -> bytes:
    msgs = example_messages()
    ex = msgs["Example"]()
    for key, value in row.items():
        feature = ex.features.feature[key]
        if value is None:
            # Null cell (e.g. a missing column filled by block_from_rows):
            # an EMPTY feature — reads back as [] (tf.Example has no null).
            feature.SetInParent()
            continue
        vals = value if isinstance(value, (list, tuple, np.ndarray)) \
            else [value]
        vals = list(np.asarray(vals).ravel()) if len(vals) and not isinstance(
            vals[0], (bytes, str)) else list(vals)
        if len(vals) == 0:
            feature.float_list.SetInParent()
        elif isinstance(vals[0], bytes):
            feature.bytes_list.value.extend(vals)
        elif isinstance(vals[0], str):
            feature.bytes_list.value.extend(v.encode() for v in vals)
        elif all(float(v).is_integer() for v in vals) and not any(
                isinstance(v, (float, np.floating)) for v in vals):
            feature.int64_list.value.extend(int(v) for v in vals)
        else:
            feature.float_list.value.extend(float(v) for v in vals)
    return ex.SerializeToString()
