"""StreamingIngest: the Trainer's streaming input path.

One ``StreamingIngest`` per named dataset lives on the controller (thread
tier, like the elastic ``SampleLedger`` it builds on) and outlives
individual attempts.  Per epoch it derives a seeded permutation of the
plan's source shards (shard-level shuffle) and a ``SampleLedger`` *over
shard indices*: workers claim shards one at a time through their
:class:`IngestShard` view and stream each claimed shard through

    backpressured executor -> windowed shuffle -> rebatch -> host
    prefetch [-> device double-buffer]

so an epoch is never materialized and host memory stays bounded by the
window budget (docs/data-ingestion.md).

Exactly-once under elastic shrink/grow works exactly like the sized-
dataset ledger, at shard granularity: a claim is provisional (tagged
``PROVISIONAL_STEP``) until the worker has pulled the shard's last block
out of its shuffle window — then it is retagged with the session's
current checkpoint step and seals when a checkpoint at/past that step
commits.  A preemption rolls incomplete shards back into the queue for
survivors; claiming IS the resplit, so a grow at an epoch boundary
distributes the next epoch over the new world with no repartition step.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.data import executor as ex
from ray_tpu.data.ingest import executor as ingest_ex
from ray_tpu.data.ingest import metrics as ingest_metrics
from ray_tpu.data.ingest.prefetch import DeviceBatchIterator, HostPrefetcher
from ray_tpu.data.ingest.shuffle import epoch_rng, window_shuffle
from ray_tpu.train.elastic import PROVISIONAL_STEP, SampleLedger
from ray_tpu.util import tracing

#: Live StreamingIngest instances (weak — an abandoned ingest must not be
#: kept alive by the registry).  The cluster autoscaler's signal collector
#: probes :func:`pending_shards` through sys.modules, so a cluster that
#: never ingests never imports this module.
_LIVE_INGESTS: "weakref.WeakSet[StreamingIngest]" = weakref.WeakSet()
_LIVE_LOCK = threading.Lock()


def pending_shards() -> int:
    """Unclaimed source shards summed across every live ingest's epochs —
    the autoscaler's train-pressure signal (also exported as the
    ``ray_tpu_data_ingest_pending_shards`` gauge)."""
    with _LIVE_LOCK:
        ingests = list(_LIVE_INGESTS)
    total = sum(st.ledger.remaining()
                for ing in ingests for st in ing._states())
    ingest_metrics.PENDING_SHARDS.set(total)
    return total


class _GaugeCounter:
    """Tiny thread-safe resident-bytes counter feeding a gauge + peak."""

    def __init__(self, gauge):
        self._gauge = gauge
        self._lock = threading.Lock()
        self._value = 0  # guarded_by: _lock
        self._peak = 0  # guarded_by: _lock

    def add(self, delta: int) -> None:
        with self._lock:
            self._value += delta
            if self._value > self._peak:
                self._peak = self._value
            value = self._value
        self._gauge.set(value)

    def value(self) -> int:
        with self._lock:
            return self._value

    def peak(self) -> int:
        with self._lock:
            return self._peak


class _ResidentBytes:
    """One epoch's share of the shared window counter, releasable.

    Adds flow through to the gauge; ``release()`` atomically zeroes the
    epoch's balance and returns it to the gauge, so an epoch abandoned
    mid-stream (elastic stop, IngestAborted, a consumer breaking out of
    ``iter_batches``) cannot leave its resident blocks counted in
    WINDOW_BYTES/peak_window_bytes forever.  Called from both the
    pipeline's thread (the prefetch pump's teardown) and the consumer
    thread (``_iter_epoch``'s finally) — whichever side runs last
    releases what the other missed; double release is a no-op.
    """

    def __init__(self, window: _GaugeCounter):
        self._window = window
        self._lock = threading.Lock()
        self._bytes = 0  # guarded_by: _lock

    def add(self, n: int) -> None:
        with self._lock:
            self._bytes += n
        self._window.add(n)

    def release(self) -> None:
        with self._lock:
            n, self._bytes = self._bytes, 0
        if n:
            self._window.add(-n)


class _EpochState:
    """Shared per-epoch claim state: shard permutation + shard ledger."""

    def __init__(self, n_shards: int, rng, seal_on_claim: bool):
        order = list(range(n_shards))
        rng.shuffle(order)
        #: claim position -> plan index (the shard-level shuffle)
        self.order = order
        self.ledger = SampleLedger(order, seal_on_claim=seal_on_claim)


class _ShardTracker:
    """Per-worker completion tracking: a claimed shard is 'consumed' when
    the batch holding its LAST row is yielded to the training loop — at
    that moment the claim is retagged from PROVISIONAL_STEP to the
    session's current checkpoint step (or sealed outright without a
    session/coordinator).  The timing is load-bearing: at yield time
    ``current_checkpoint_step()`` is the step the consumer's next report
    gets, i.e. the first checkpoint whose state contains those rows — tag
    earlier and a restore to a committed step could seal rows it never
    trained (silent loss); tag later and a fully-consumed shard would
    requeue on a grow (double-train).  Rows yielded but never followed by
    a report stay provisional and requeue — the safe direction.

    Threading: with prefetch on (the default) ``entered()`` and
    ``shard_produced()`` run on the pump thread while ``block_done()``
    runs on the consumer thread — the shuffle window can emit a shard's
    early blocks for consumption while its later blocks are still
    entering — so the counters are lock-guarded and the consumed
    transition is decided under the lock (exactly one side observes it).
    """

    def __init__(self, ledger: SampleLedger, session=None):
        self._ledger = ledger
        self._session = session
        self._lock = threading.Lock()
        #: pos -> blocks in flight past entry, not yet consumed
        self._blocks: Dict[int, int] = {}  # guarded_by: _lock
        #: pos -> total blocks, once the shard fully produced
        self._produced: Dict[int, int] = {}  # guarded_by: _lock

    def entered(self, pos: int) -> None:
        with self._lock:
            self._blocks[pos] = self._blocks.get(pos, 0) + 1

    def shard_produced(self, pos: int, n_blocks: int) -> None:
        with self._lock:
            self._produced[pos] = n_blocks
            consumed = self._consumed_locked(pos)
        if consumed:
            self._retag(pos)

    def block_done(self, pos: int) -> None:
        with self._lock:
            self._blocks[pos] -= 1
            consumed = self._consumed_locked(pos)
        if consumed:
            self._retag(pos)

    def _consumed_locked(self, pos: int) -> bool:
        if self._blocks.get(pos, 0) != 0 or pos not in self._produced:
            return False
        self._blocks.pop(pos, None)
        del self._produced[pos]
        return True

    def _retag(self, pos: int) -> None:
        step = (self._session.current_checkpoint_step()
                if self._session is not None else None)
        self._ledger.retag((pos,), step)


def _rebatch_tracked(stream, batch_size: Optional[int], batch_format: str):
    """``ray_tpu.data.block.rebatch`` with row provenance: yields
    ``(done, batch)`` where ``done`` lists shard positions whose every row
    is contained in batches yielded so far (this one included).  The
    trailing flush can yield ``(done, None)`` when positions finish with
    no rows left to batch (empty blocks at stream end)."""
    from ray_tpu.data.block import BlockAccessor, concat_blocks

    carry: List[Any] = []
    carry_rows = 0
    fifo: deque = deque()  # (pos, rows still unemitted) in row order
    done: List[int] = []

    def emit(n: int) -> tuple:
        while n:
            pos, rows = fifo[0]
            take = min(rows, n)
            rows -= take
            n -= take
            if rows == 0:
                fifo.popleft()
                done.append(pos)
            else:
                fifo[0] = (pos, rows)
        out = tuple(done)
        done.clear()
        return out

    for pos, block in stream:
        nrows = block.num_rows
        if nrows == 0:
            done.append(pos)
            continue
        fifo.append((pos, nrows))
        if batch_size is None:
            yield emit(nrows), BlockAccessor(block).to_batch(batch_format)
            continue
        carry.append(block)
        carry_rows += nrows
        while carry_rows >= batch_size:
            merged = concat_blocks(carry)
            acc = BlockAccessor(merged)
            yield (emit(batch_size),
                   BlockAccessor(acc.slice(0, batch_size))
                   .to_batch(batch_format))
            rest = acc.slice(batch_size, acc.num_rows())
            carry = [rest] if rest.num_rows > 0 else []
            carry_rows = acc.num_rows() - batch_size
    if carry_rows:
        yield (emit(carry_rows),
               BlockAccessor(concat_blocks(carry)).to_batch(batch_format))
    if done:
        yield tuple(done), None


class StreamingIngest:
    """Controller-side streaming input for one named dataset."""

    def __init__(self, dataset, *, window_blocks: int = 16,
                 window_bytes: int = 128 << 20,
                 seed: Optional[int] = None,
                 prefetch_batches: int = 2,
                 seal_on_claim: bool = True):
        self._plans = ingest_ex.shard_plans(dataset._op)
        self._window_blocks = max(1, window_blocks)
        self._window_bytes = max(1 << 20, window_bytes)
        self._seed = seed
        self._prefetch_batches = max(0, prefetch_batches)
        self._seal_on_claim = seal_on_claim
        self._lock = threading.Lock()
        self._epochs: Dict[int, _EpochState] = {}  # guarded_by: _lock
        self._window = _GaugeCounter(ingest_metrics.WINDOW_BYTES)
        #: plan index -> object locality ("" local / addr / None unknown),
        #: computed once — input placements don't move under the epoch.
        self._localities: Optional[List[Optional[str]]] = None
        with _LIVE_LOCK:
            _LIVE_INGESTS.add(self)

    # ------------------------------------------------------------- shape
    def num_shards(self) -> int:
        return len(self._plans)

    def _plan_localities(self) -> List[Optional[str]]:
        """Per-plan object locality, computed lazily once (a soft hint:
        a stale entry costs one remote fetch, never correctness)."""
        with self._lock:
            if self._localities is None:
                self._localities = [ingest_ex.plan_locality(p)
                                    for p in self._plans]
            return self._localities

    @property
    def peak_window_bytes(self) -> int:
        """High-water mark of bytes resident in shuffle windows + fetch
        buffers across all workers — the soak test's RSS-bound proxy."""
        return self._window.peak()

    @property
    def resident_window_bytes(self) -> int:
        """Bytes currently counted resident across all epochs/workers;
        returns to zero once every epoch finishes or is released."""
        return self._window.value()

    def make_shard(self, session=None) -> "IngestShard":
        return IngestShard(self, session)

    # -------------------------------------------------- per-epoch state
    def _epoch_state(self, epoch: int) -> _EpochState:
        with self._lock:
            st = self._epochs.get(epoch)
            if st is None:
                st = _EpochState(len(self._plans),
                                 epoch_rng(self._seed, epoch),
                                 self._seal_on_claim)
                self._epochs[epoch] = st
            return st

    def _states(self) -> List[_EpochState]:
        with self._lock:
            return list(self._epochs.values())

    # ------------------------------------- ledger protocol (controller)
    # The trainer drives these exactly like a sized dataset's ledger —
    # delegation across every epoch touched so far.
    def seal(self, committed_step: int) -> int:
        return sum(st.ledger.seal(committed_step) for st in self._states())

    def finish(self) -> int:
        """Clean finish: seal every claim that actually trained (retagged
        with a real step at the yield of its last batch) and roll back
        claims still tagged PROVISIONAL_STEP — shards the prefetch pump
        claimed whose batches the user loop never consumed (e.g. a
        fixed-steps loop breaking out of ``iter_batches`` mid-epoch) must
        not audit as trained.  A blanket ``seal_all`` here would report
        never-trained shards as trained.  Returns how many never-consumed
        claims were rolled back."""
        return sum(st.ledger.rollback(PROVISIONAL_STEP - 1)
                   for st in self._states())

    def rollback(self, restore_step: Optional[int]) -> int:
        return sum(st.ledger.rollback(restore_step)
                   for st in self._states())

    def exhausted(self) -> bool:
        return all(st.ledger.exhausted() for st in self._states())

    def reset(self) -> None:
        """Non-elastic restart: the attempt re-runs the user loop from its
        own epoch 0, so ingest epochs must start fresh too."""
        with self._lock:
            self._epochs = {}

    # --------------------------------------------------------- auditing
    def audit(self, epoch: int = 0) -> Dict[str, Any]:
        """Exactly-once accounting for one epoch, in shard-id space."""
        with self._lock:
            st = self._epochs.get(epoch)
        if st is None:
            return {"trained_counts": {}, "double_trained": [],
                    "untrained": list(range(len(self._plans)))}
        counts = st.ledger.trained_counts()
        return {
            "trained_counts": {st.order[p]: c for p, c in counts.items()},
            "double_trained": [st.order[p]
                               for p in st.ledger.double_trained()],
            "untrained": [st.order[p] for p in st.ledger.untrained()],
        }

    def epochs_started(self) -> List[int]:
        with self._lock:
            return sorted(self._epochs)

    # ------------------------------------------------------ worker side
    def _iter_epoch(self, epoch: int, session, batch_size: Optional[int],
                    batch_format: str, prefetch_batches: Optional[int],
                    device_sharding=None) -> Iterator[Dict[str, Any]]:
        from ray_tpu.data.block import BlockAccessor

        st = self._epoch_state(epoch)
        tracker = _ShardTracker(st.ledger, session)
        fence = session.stop_requested if session is not None else None
        resident = _ResidentBytes(self._window)

        # Locality-aware claiming: prefer shards whose object copies live
        # on the reading node ("" = local), so a scale-out does not turn
        # the data plane into a cross-node fetch storm.  Purely a claim
        # ORDER preference — every shard is still claimed exactly once.
        localities = self._plan_localities()
        has_locality = any(a is not None for a in localities)

        def _prefer_local(pos: int) -> bool:
            return localities[st.order[pos]] == ""

        def plan_iter():
            while True:
                t0 = time.time()
                got = st.ledger.claim(
                    1, step=PROVISIONAL_STEP, fence=fence,
                    prefer=_prefer_local if has_locality else None)
                if got is None:
                    ingest_metrics.PENDING_SHARDS.set(st.ledger.remaining())
                    return
                pos = got[0]
                if not has_locality:
                    outcome = "blind"
                else:
                    outcome = "local" if localities[st.order[pos]] == "" \
                        else "remote"
                ingest_metrics.LOCALITY_CLAIMS.inc(
                    1, tags={"locality": outcome})
                tracing.record_span(
                    "data.locality_claim", t0, time.time(),
                    attributes={"preferred": has_locality,
                                "local": outcome == "local"})
                ingest_metrics.PENDING_SHARDS.set(st.ledger.remaining())
                yield pos, self._plans[st.order[pos]]

        should_stop = fence.is_set if fence is not None else None
        budget = ex.ResourceBudget(mem_budget=self._window_bytes)
        stream = ingest_ex.stream_blocks(
            plan_iter(), budget, on_shard_end=tracker.shard_produced,
            should_stop=should_stop)

        def into_window():
            for pos, block in stream:
                try:
                    nbytes = BlockAccessor(block).size_bytes()
                except Exception:
                    nbytes = 0
                tracker.entered(pos)
                resident.add(nbytes)
                yield pos, block, nbytes

        salt = (session.context.world_rank + 1) if session is not None else 0
        shuffled = window_shuffle(
            into_window(), self._window_blocks,
            epoch_rng(self._seed, epoch, salt=salt),
            size_of=lambda t: t[2], max_bytes=self._window_bytes)

        def blocks_out():
            for pos, block, nbytes in shuffled:
                resident.add(-nbytes)
                yield pos, block

        def released(it):
            # Runs on the chain's own thread (the prefetch pump when
            # prefetch is on): whether the pipeline ends normally
            # (residual already 0), raises, or is closed after an
            # abandoned epoch, this epoch's residual leaves the gauge.
            try:
                yield from it
            finally:
                resident.release()

        tagged = released(_rebatch_tracked(blocks_out(), batch_size,
                                           batch_format))
        depth = (self._prefetch_batches if prefetch_batches is None
                 else prefetch_batches)
        prefetcher = HostPrefetcher(tagged, depth=depth,
                                    should_stop=should_stop) \
            if depth > 0 else tagged
        src: Any = prefetcher
        if device_sharding is not None:
            # Align each transferred batch with its provenance: the device
            # iterator pulls one batch ahead, so `done` sets queue up and
            # pop in yield order — retag still lands at the batch's yield,
            # never at its early transfer dispatch.
            dones: deque = deque()

            def only_batches(it):
                for done, batch in it:
                    if batch is None:
                        for pos in done:
                            tracker.block_done(pos)
                        continue
                    dones.append(done)
                    yield batch

            src = ((dones.popleft(), batch) for batch in
                   DeviceBatchIterator(only_batches(prefetcher),
                                       sharding=device_sharding))
        try:
            for done, batch in src:
                for pos in done:
                    tracker.block_done(pos)
                if batch is not None:
                    yield batch
        finally:
            if isinstance(prefetcher, HostPrefetcher):
                prefetcher.close()
            # Consumer-side backstop: without prefetch the chain runs on
            # THIS thread and is merely suspended here, so its own finally
            # has not fired; with prefetch the pump's teardown release may
            # lag — drain what is resident now, the pump releases the rest
            # at its exit (release() is an atomic drain, never double).
            resident.release()


class IngestShard:
    """A worker's view of a shared :class:`StreamingIngest` — what
    ``train.get_dataset_shard()`` returns on the streaming path.  Like
    ``DataIterator`` it is re-iterable: each ``iter_batches()`` call
    consumes one fresh epoch (shared across workers via the per-epoch
    shard ledger)."""

    def __init__(self, ingest: StreamingIngest, session=None):
        self._ingest = ingest
        self._session = session
        self._epoch = 0

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: Optional[int] = None,
                     device_sharding=None) -> Iterator[Dict[str, Any]]:
        epoch = self._epoch
        self._epoch += 1
        return self._ingest._iter_epoch(
            epoch, self._session, batch_size, batch_format,
            prefetch_batches, device_sharding)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for batch in self.iter_batches(batch_size=None):
            n = len(next(iter(batch.values()))) if batch else 0
            for i in range(n):
                yield {k: v[i] for k, v in batch.items()}

    def num_shards(self) -> int:
        return self._ingest.num_shards()
