"""Offset-sharded file readers: reader parallelism beyond file count.

One read task per *file* caps parallelism at however many files the
dataset happens to have — one giant TFRecord shard serializes the whole
pipeline.  These builders split a single file into ``shards_per_file``
range shards:

* TFRecord: byte ranges.  A shard owns every record whose HEADER offset
  falls in its ``[start, end)`` range; a shard starting mid-record scans
  forward to the next CRC-verified frame boundary
  (``tfrecords.read_records_range``), so shards are disjoint and exactly
  cover the file without an index.
* Parquet: row-group ranges via the file's own metadata (row groups are
  parquet's native parallelism unit — no scanning needed).

Wired into ``data.read_tfrecords`` / ``data.read_parquet`` through their
``shards_per_file=`` argument.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional


def tfrecord_range_tasks(path: str,
                         shards_per_file: int) -> List[Callable[[], object]]:
    """Read tasks covering ``path`` in ``shards_per_file`` byte ranges."""
    size = os.path.getsize(path)
    shards = max(1, int(shards_per_file))
    if size == 0 or shards == 1:
        def read_all(path=path):
            from ray_tpu.data.tfrecords import examples_to_block, read_records

            return examples_to_block(read_records(path))

        return [read_all]
    bounds = [size * i // shards for i in range(shards + 1)]

    def make_task(start: int, end: int):
        def read():
            from ray_tpu.data.tfrecords import (
                examples_to_block,
                read_records_range,
            )

            return examples_to_block(read_records_range(path, start, end))

        return read

    return [make_task(lo, hi)
            for lo, hi in zip(bounds, bounds[1:]) if hi > lo]


def parquet_range_tasks(path: str, shards_per_file: int,
                        columns: Optional[List[str]] = None,
                        ) -> List[Callable[[], object]]:
    """Read tasks covering ``path``'s row groups in contiguous ranges."""
    import pyarrow.parquet as pq

    def read_all(path=path):
        return pq.read_table(path, columns=columns)

    shards = max(1, int(shards_per_file))
    if shards == 1:
        return [read_all]
    n_groups = pq.ParquetFile(path).metadata.num_row_groups
    if n_groups == 0:
        # No row groups to range over (empty file): keep the single
        # read_all task so the file still contributes its (empty) block —
        # and its schema — instead of silently dropping out of the plan.
        return [read_all]
    shards = min(shards, n_groups)
    bounds = [n_groups * i // shards for i in range(shards + 1)]

    def make_task(lo: int, hi: int):
        def read():
            pf = pq.ParquetFile(path)
            return pf.read_row_groups(list(range(lo, hi)), columns=columns)

        return read

    return [make_task(lo, hi)
            for lo, hi in zip(bounds, bounds[1:]) if hi > lo]
