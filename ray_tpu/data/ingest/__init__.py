"""Streaming training-ingestion subsystem (docs/data-ingestion.md).

Backpressured plan execution -> per-epoch windowed shuffle -> rebatch ->
host prefetch -> optional double-buffered device transfer, with
shard-level exactly-once accounting under elastic world changes.
"""

from ray_tpu.data.ingest.executor import (
    fetch_block,
    shard_plans,
    shardable,
    stream_blocks,
)
from ray_tpu.data.ingest.ingest import IngestShard, StreamingIngest
from ray_tpu.data.ingest.prefetch import DeviceBatchIterator, HostPrefetcher
from ray_tpu.data.ingest.readers import parquet_range_tasks, tfrecord_range_tasks
from ray_tpu.data.ingest.shuffle import epoch_rng, window_shuffle

__all__ = [
    "DeviceBatchIterator",
    "HostPrefetcher",
    "IngestShard",
    "StreamingIngest",
    "epoch_rng",
    "fetch_block",
    "parquet_range_tasks",
    "shard_plans",
    "shardable",
    "stream_blocks",
    "tfrecord_range_tasks",
    "window_shuffle",
]
