"""Windowed shuffle: larger-than-memory randomization at O(window) memory.

(ref: the reference's local_shuffle_buffer_size on iter_batches — a
bounded reservoir between the block stream and the batcher.)  A full
random_shuffle materializes the epoch; the window holds at most W items
(and optionally a byte budget) and emits a uniformly-random resident item
each time a new one arrives, so randomization quality degrades gracefully
with memory instead of falling off a cliff.  Combined with the per-epoch
shard-order permutation in ingest.py (which shuffles at the source level),
two rows that were adjacent on disk can land an entire epoch apart while
the window itself stays small.
"""

from __future__ import annotations

import random
from typing import Callable, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")


def window_shuffle(items: Iterable[T], window: int,
                   rng: random.Random, *,
                   size_of: Optional[Callable[[T], int]] = None,
                   max_bytes: Optional[int] = None) -> Iterator[T]:
    """Yield every item of ``items`` exactly once, shuffled within a
    sliding window of ``window`` items (optionally also capped at
    ``max_bytes`` via ``size_of``).  ``window <= 1`` is a passthrough.

    Emission rule: once the buffer is full, swap a uniformly-random
    resident item to the tail and pop it — each emission is uniform over
    the current window, and an item admitted at input position p is
    emitted no later than output position p + window (bounded delay =
    bounded memory).  The tail drains fully shuffled.
    """
    buf: list = []
    buf_bytes = 0
    for item in items:
        buf.append(item)
        if size_of is not None:
            buf_bytes += size_of(item)
        while len(buf) >= max(window, 1) or (
                max_bytes is not None and size_of is not None
                and buf_bytes > max_bytes and len(buf) > 1):
            j = rng.randrange(len(buf))
            buf[j], buf[-1] = buf[-1], buf[j]
            out = buf.pop()
            if size_of is not None:
                buf_bytes -= size_of(out)
            yield out
    rng.shuffle(buf)
    for out in buf:
        yield out


def epoch_rng(seed: Optional[int], epoch: int, salt: int = 0) -> random.Random:
    """Deterministic per-epoch RNG: a fixed seed reproduces the exact same
    epoch order; consecutive epochs differ (the reference reshuffles per
    epoch too).  ``seed=None`` derives a random base once per process."""
    if seed is None:
        seed = _PROCESS_SEED
    return random.Random((seed * 1_000_003 + epoch) ^ (salt * 7_919))


_PROCESS_SEED = random.SystemRandom().getrandbits(48)
