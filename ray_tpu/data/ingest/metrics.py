"""Streaming-ingest metrics.

Declared at import time like the serve/checkpoint/train metric modules so
``scripts/check_metrics.py`` lints them; exported on ``/metrics`` through
the process registry (util/metrics.py).

The anchor set is what an operator tuning an input pipeline needs: how
fast rows flow into training, whether the prefetch buffer is keeping the
step fed (occupancy), and how much step time the pipeline is costing when
it is not (starved seconds — the number that says "your input pipeline is
the bottleneck, raise prefetch_batches / reader parallelism").
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge

ROWS = Counter(
    "ray_tpu_data_ingest_rows_total",
    "Rows streamed into training by the ingest pipeline (rate = rows/s)",
)

BYTES = Counter(
    "ray_tpu_data_ingest_bytes_total",
    "Bytes of block data fetched from the object store by the ingest "
    "pipeline",
)

SHARDS = Counter(
    "ray_tpu_data_ingest_shards_total",
    "Source shards claimed and fully streamed by ingest workers",
)

FETCH_RETRIES = Counter(
    "ray_tpu_data_ingest_fetch_retries_total",
    "Block fetches retried after a transient failure (lost object, "
    "injected chaos) before training observed anything",
)

PREFETCH_OCCUPANCY = Gauge(
    "ray_tpu_data_ingest_prefetch_occupancy",
    "Batches currently buffered in the host prefetcher (0 while the "
    "training loop is outrunning the pipeline)",
)

WINDOW_BYTES = Gauge(
    "ray_tpu_data_ingest_window_bytes",
    "Bytes of block data currently resident in the shuffle window + "
    "fetch-ahead buffer (bounded by DatasetConfig.window_bytes)",
)

STARVED_SECONDS = Counter(
    "ray_tpu_data_ingest_starved_seconds_total",
    "Seconds the training loop spent blocked on an empty prefetch buffer "
    "(step starvation caused by the input pipeline)",
)

LOCAL_BYTES = Counter(
    "ray_tpu_data_ingest_local_bytes_total",
    "Block bytes materialized from this node's own object store "
    "(including local spill restores) — the locality-aware claimer's win",
)

CROSS_NODE_BYTES = Counter(
    "ray_tpu_data_ingest_cross_node_bytes_total",
    "Block bytes pulled over the object plane from another node — what "
    "locality-aware shard claiming exists to minimize",
)

SPILL_REFETCHES = Counter(
    "ray_tpu_data_ingest_spill_refetch_total",
    "Blocks restored from this node's local spill files instead of "
    "refetched over the network (spill-aware refetch)",
)

LOCALITY_CLAIMS = Counter(
    "ray_tpu_data_ingest_locality_claims_total",
    "Shard claims by locality outcome: 'local' when the claimed shard's "
    "object copies live on the reading node, 'remote' otherwise, 'blind' "
    "when the plan carries no locality information",
    tag_keys=("locality",),
)

PENDING_SHARDS = Gauge(
    "ray_tpu_data_ingest_pending_shards",
    "Unclaimed source shards summed across live streaming ingests (a "
    "cluster-autoscaler train-pressure signal)",
)
