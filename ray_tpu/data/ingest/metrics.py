"""Streaming-ingest metrics.

Declared at import time like the serve/checkpoint/train metric modules so
``scripts/check_metrics.py`` lints them; exported on ``/metrics`` through
the process registry (util/metrics.py).

The anchor set is what an operator tuning an input pipeline needs: how
fast rows flow into training, whether the prefetch buffer is keeping the
step fed (occupancy), and how much step time the pipeline is costing when
it is not (starved seconds — the number that says "your input pipeline is
the bottleneck, raise prefetch_batches / reader parallelism").
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge

ROWS = Counter(
    "ray_tpu_data_ingest_rows_total",
    "Rows streamed into training by the ingest pipeline (rate = rows/s)",
)

BYTES = Counter(
    "ray_tpu_data_ingest_bytes_total",
    "Bytes of block data fetched from the object store by the ingest "
    "pipeline",
)

SHARDS = Counter(
    "ray_tpu_data_ingest_shards_total",
    "Source shards claimed and fully streamed by ingest workers",
)

FETCH_RETRIES = Counter(
    "ray_tpu_data_ingest_fetch_retries_total",
    "Block fetches retried after a transient failure (lost object, "
    "injected chaos) before training observed anything",
)

PREFETCH_OCCUPANCY = Gauge(
    "ray_tpu_data_ingest_prefetch_occupancy",
    "Batches currently buffered in the host prefetcher (0 while the "
    "training loop is outrunning the pipeline)",
)

WINDOW_BYTES = Gauge(
    "ray_tpu_data_ingest_window_bytes",
    "Bytes of block data currently resident in the shuffle window + "
    "fetch-ahead buffer (bounded by DatasetConfig.window_bytes)",
)

STARVED_SECONDS = Counter(
    "ray_tpu_data_ingest_starved_seconds_total",
    "Seconds the training loop spent blocked on an empty prefetch buffer "
    "(step starvation caused by the input pipeline)",
)
