"""Backpressured streaming execution for training ingest.

Two pieces the higher-level ``StreamingIngest`` composes:

* :func:`shard_plans` — split a lazy logical plan into independent
  per-source sub-plans (one per read task / input block) so workers can
  claim and execute sources individually.  Only per-block map chains are
  shardable; a plan with an all-to-all stage (shuffle/sort/groupby/...)
  degrades to a single shard — the whole pipeline is then one claim, still
  streamed with backpressure but not work-stealable.
* :func:`stream_blocks` — pull block refs through the existing
  plan executor (``ray_tpu.data.executor.execute``) with a bounded
  fetch-ahead buffer (reusing :class:`ResourceBudget`'s learned-block-size
  byte cap), materializing each block through :func:`fetch_block`, the
  retrying fault-point-instrumented object-store get.

The stream is pull-based end to end: a slow training step stops new read
tasks at the next cap check, so host memory stays bounded at
``window_bytes`` regardless of dataset size (ref: the reference's
streaming_executor resource budgets + backpressure policies).
"""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu._private import fault_injection
from ray_tpu.data import executor as ex
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.ingest import metrics as ingest_metrics
from ray_tpu.data.plan import AbstractMap, InputData, LogicalOp, Read
from ray_tpu.exceptions import GetTimeoutError, RayTpuError, WorkerCrashedError
from ray_tpu.util import tracing

#: Bounded retries for a lost/failed block fetch before surfacing the
#: error to the training loop.
FETCH_RETRIES = 3

#: Ceiling on one fetch attempt; a block that hasn't materialized by then
#: is treated as lost (its shard claim rolls back with the attempt).
FETCH_TIMEOUT_S = 60.0


class IngestAborted(RayTpuError):
    """The owning session was stopped while the pipeline was stalled.

    Raised instead of waiting out a full fetch timeout on objects that
    died with a preempted node — elastic teardown must release the
    worker (and its gang-scheduled CPU) promptly so the shrunken attempt
    can reserve its placement group.  The aborted shard's claim stays
    provisional and is requeued by the rollback.
    """


def shardable(op: LogicalOp) -> bool:
    """True when every op past the source is a task-pool per-block map —
    the ops whose semantics are preserved under per-source splitting.
    Actor-pool maps share a stateful pool (one pool per sub-plan would
    multiply actors), and all-to-all ops need the whole stream."""
    chain = op.chain()
    if not isinstance(chain[0], (Read, InputData)):
        return False
    return all(isinstance(o, AbstractMap) and o.compute.kind == "tasks"
               for o in chain[1:])


def shard_plans(op: LogicalOp) -> List[LogicalOp]:
    """Split ``op`` into one sub-plan per source shard (read task / input
    block), each a shallow rewiring of the downstream map chain.  Falls
    back to ``[op]`` when the plan is not shardable."""
    if not shardable(op):
        return [op]
    chain = op.chain()
    root, rest = chain[0], chain[1:]
    if isinstance(root, Read):
        sources: List[LogicalOp] = [Read([t], schema_hint=root.schema_hint)
                                    for t in root.read_tasks]
    else:
        sources = [InputData([b]) for b in root.blocks]
    return [_rewire(src, rest) for src in sources]


def _rewire(source: LogicalOp, rest: Iterable[LogicalOp]) -> LogicalOp:
    cur = source
    for o in rest:
        clone = copy.copy(o)
        clone.input_op = cur
        cur = clone
    return cur


def plan_locality(plan: LogicalOp) -> Optional[str]:
    """Object-plane address where a shard sub-plan's input objects live,
    for locality-aware claiming: ``""`` means the reading node itself,
    an address string names the remote node holding the copies, ``None``
    means no locality information (``Read`` roots — the data is not an
    object yet — or raw in-memory blocks, or mixed placements).

    Spill-aware: a locally-spilled object still classifies as local
    (``_remote_owner_addr`` consults the authoritative location table,
    not residency) — restoring from this node's spill files is cheaper
    than any network fetch, so spilled shards must not lose their
    locality preference."""
    root = plan.chain()[0]
    if not isinstance(root, InputData):
        return None
    try:
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
    except Exception:  # noqa: BLE001 — no runtime, no locality
        return None
    addrs = set()
    for b in root.blocks:
        if getattr(b, "id", None) is None:
            return None  # raw in-memory block: no placement to honor
        try:
            addrs.add(rt._remote_owner_addr(b))
        except Exception:  # noqa: BLE001
            return None
    return addrs.pop() if len(addrs) == 1 else None


def block_source(ref) -> str:
    """Where a block ref's bytes come from at fetch time: ``local``
    (this node's store), ``spilled`` (local store, restored from this
    node's spill files — still no network), or ``remote``."""
    oid = getattr(ref, "id", None)
    if oid is None:
        return "local"
    try:
        from ray_tpu._private.runtime import get_runtime

        rt = get_runtime()
        if rt._remote_owner_addr(ref):
            return "remote"
        if rt.store.state_of(oid) == "SPILLED":
            return "spilled"
    except Exception:  # noqa: BLE001 — classification is best-effort
        pass
    return "local"


@ray_tpu.remote(num_cpus=0)
def _fused_shard_task(read_task, transforms):
    block = read_task()
    for t in transforms:
        block = t(block)
    return block


@ray_tpu.remote(num_cpus=0)
def _fused_block_task(block, transforms):
    for t in transforms:
        block = t(block)
    return block


def _exec_subplan(plan: LogicalOp) -> Iterator[Any]:
    """Yield block refs for one shard sub-plan.

    A shardable sub-plan (single source + task-map chain) fuses into ONE
    zero-CPU task: read + every map transform in a single hop.  Zero CPU
    is load-bearing, not an optimization — training gang-reserves whole
    cores via its placement group, and on a cluster with no spare cores a
    1-CPU read task would deadlock the input pipeline against the very
    workers waiting on it.  The I/O-bound data plane rides along instead
    of competing.  Non-shardable fallbacks (all-to-all stages) keep the
    general executor and its resource accounting.
    """
    chain = plan.chain()
    root = chain[0]
    if all(isinstance(o, AbstractMap) and o.compute.kind == "tasks"
           for o in chain[1:]):
        transforms = [ex.make_block_transform(o) for o in chain[1:]]
        if isinstance(root, Read) and len(root.read_tasks) == 1:
            yield _fused_shard_task.remote(root.read_tasks[0], transforms)
            return
        if isinstance(root, InputData) and len(root.blocks) == 1:
            yield _fused_block_task.remote(root.blocks[0], transforms)
            return
    yield from ex.execute(plan)


def _get_abortable(ref, should_stop: Optional[Callable[[], bool]]):
    """ray_tpu.get that aborts a STALLED fetch once the session stops.

    Healthy fetches never observe the stop — the check only runs after a
    poll times out, so a graceful grow stop still drains in-flight
    claimed shards cleanly."""
    deadline = time.monotonic() + FETCH_TIMEOUT_S
    while True:
        remaining = deadline - time.monotonic()
        try:
            return ray_tpu.get(ref, timeout=min(2.0, max(0.05, remaining)))
        except GetTimeoutError:
            if should_stop is not None and should_stop():
                raise IngestAborted(
                    "session stopped while a block fetch was stalled "
                    "(object likely lost with a preempted node)")
            if remaining <= 0:
                raise


def fetch_block(ref, retries: int = FETCH_RETRIES,
                should_stop: Optional[Callable[[], bool]] = None):
    """Materialize a block ref with bounded retries.

    The ``data_ingest_fetch`` fault point models the fetch failing
    transiently (the producing task's node died and the object must be
    reconstructed, or chaos injected it); training never observes the
    failure unless every retry burns — a torn batch is impossible because
    nothing is yielded until the whole block materialized."""
    last: Optional[BaseException] = None
    for attempt in range(retries + 1):
        source = block_source(ref)  # classify BEFORE the get pulls it local
        try:
            fault_injection.check("data_ingest_fetch")
            block = _get_abortable(ref, should_stop)
        except IngestAborted:
            raise
        except WorkerCrashedError as e:
            last = e
            ingest_metrics.FETCH_RETRIES.inc()
            continue
        acc = BlockAccessor(block)
        ingest_metrics.ROWS.inc(acc.num_rows())  # inc(0) is a no-op
        try:
            nbytes = acc.size_bytes()
            ingest_metrics.BYTES.inc(nbytes)
            # Locality accounting: cross-node bytes are what the
            # locality-aware claimer minimizes; a local spill restore
            # counts as local traffic (and is tallied as a spill refetch).
            if source == "remote":
                ingest_metrics.CROSS_NODE_BYTES.inc(nbytes)
            else:
                ingest_metrics.LOCAL_BYTES.inc(nbytes)
                if source == "spilled":
                    ingest_metrics.SPILL_REFETCHES.inc()
        except Exception:
            pass
        return block
    raise last  # type: ignore[misc]


def stream_blocks(plans: Iterator[Tuple[Any, LogicalOp]],
                  budget: Optional[ex.ResourceBudget] = None,
                  on_shard_end: Optional[Callable[[Any, int], None]] = None,
                  should_stop: Optional[Callable[[], bool]] = None,
                  ) -> Iterator[Tuple[Any, Any]]:
    """Yield ``(shard_key, block)`` across a lazy sequence of sub-plans.

    ``plans`` is pulled lazily — advancing it is what claims the next
    source shard, so claim order tracks consumption, not construction.
    Up to ``budget.cap()`` produced-but-unfetched block refs are buffered
    ahead (the byte-aware cap tightens as block sizes are learned);
    ``on_shard_end(key, n_blocks)`` fires once a shard's last block has
    been *yielded* downstream.  One retroactive ``data.ingest`` span per
    shard covers first-pull -> last-block-yield.
    """
    if budget is None:
        budget = ex.ResourceBudget()
    refs: deque = deque()  # fetched-ahead (key, ref)
    outstanding: dict = {}  # key -> blocks yielded to go (count in refs)
    totals: dict = {}  # key -> blocks produced so far (monotonic)
    produced: dict = {}  # key -> total blocks produced (shard done)
    started: dict = {}  # key -> first-pull timestamp (span start)
    gen: Optional[Iterator[Any]] = None
    cur_key: Any = None
    exhausted = False

    def _shard_done(key) -> None:
        n = produced.pop(key)
        totals.pop(key, None)
        outstanding.pop(key, None)
        ingest_metrics.SHARDS.inc()
        t0 = started.pop(key, None)
        if t0 is not None:
            tracing.record_span("data.ingest", t0, time.time(),
                                attributes={"shard": key, "blocks": n})
        if on_shard_end is not None:
            on_shard_end(key, n)

    while True:
        while not exhausted and len(refs) < budget.cap():
            if gen is None:
                try:
                    cur_key, plan = next(plans)
                except StopIteration:
                    exhausted = True
                    break
                started[cur_key] = time.time()
                outstanding[cur_key] = 0
                gen = _exec_subplan(plan)
            try:
                ref = next(gen)
            except StopIteration:
                # Record the shard's full block count, not the in-flight
                # depth — blocks already yielded downstream decremented
                # ``outstanding``, so it undercounts whenever the shard
                # outlasts the fetch-ahead window.
                produced[cur_key] = totals.get(cur_key, 0)
                if outstanding.get(cur_key, 0) == 0:
                    _shard_done(cur_key)  # all blocks already yielded
                gen = None
                continue
            budget.observe_ref(ref)
            totals[cur_key] = totals.get(cur_key, 0) + 1
            outstanding[cur_key] = outstanding.get(cur_key, 0) + 1
            refs.append((cur_key, ref))
        if not refs:
            if exhausted:
                return
            continue
        key, ref = refs.popleft()
        yield key, fetch_block(ref, should_stop=should_stop)
        outstanding[key] -= 1
        if outstanding[key] == 0 and key in produced:
            _shard_done(key)
