"""Batch prefetch: host-side buffering + double-buffered device transfer.

Two stages, independently optional:

* :class:`HostPrefetcher` — a background thread pulls batches out of the
  (backpressured) ingest pipeline into a bounded queue so block fetch /
  shuffle / rebatch latency overlaps the training step.  Occupancy and
  starved-seconds are exported as metrics: occupancy pinned at 0 plus a
  growing starved counter is the "input-bound" signature.
* :class:`DeviceBatchIterator` — dispatches ``jax.device_put`` of batch
  N+1 while the caller steps on batch N (JAX transfers are asynchronous,
  so the dispatch returns immediately and the copy proceeds during the
  step).  With a ``sharding`` (e.g. ``mesh.batch_sharding(mesh)``) the
  arrays land already laid out for the step's NamedSharding — no repack
  on first use.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, Dict, Iterable, Iterator, Optional

from ray_tpu._private import fault_injection
from ray_tpu.data.ingest import metrics as ingest_metrics
from ray_tpu.exceptions import WorkerCrashedError
from ray_tpu.util import tracing

_END = ("end", None)


def _profiler_record(bucket: str, start: float, end: float) -> None:
    """Attribute an interval to the train step profiler when one is active
    on this thread (the consumer side of the pipeline IS the train worker
    thread).  Probed via sys.modules — the data layer must not import the
    train package (trainer -> collective import chain), and if the
    profiler module was never imported, none can be active."""
    mod = sys.modules.get("ray_tpu.train.profiler")
    if mod is not None:
        mod.record(bucket, start, end)


class HostPrefetcher:
    """Pull ``src`` on a daemon thread into a queue of ``depth`` batches.

    Errors from the pipeline propagate to the consumer at the point they
    occurred in the stream (never silently truncate an epoch); ``close()``
    releases the pump thread even when the consumer abandons the iterator
    mid-epoch (elastic stop, grow boundary).
    """

    def __init__(self, src: Iterable[Any], depth: int = 2,
                 should_stop=None):
        # ``_q`` and ``_stop`` are the only pump<->consumer channels
        # (thread-safe by construction); the source iterator itself is
        # advanced exclusively on the pump thread.
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._should_stop = should_stop
        self._thread = threading.Thread(
            target=self._pump, args=(iter(src),), daemon=True,
            name="ingest-prefetch")
        self._thread.start()

    def _pump(self, src: Iterator[Any]) -> None:
        try:
            for item in src:
                if not self._put(("item", item)):
                    return
                ingest_metrics.PREFETCH_OCCUPANCY.set(self._q.qsize())
            self._put(_END)
        except BaseException as e:  # noqa: BLE001 — must reach the consumer
            self._put(("error", e))

    def _put(self, msg) -> bool:
        """Bounded put that aborts when the consumer closed us — an
        abandoned epoch must not leave a thread parked on a full queue."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> Iterator[Any]:
        try:
            while True:
                try:
                    kind, item = self._q.get_nowait()
                except queue.Empty:
                    # The step outran the pipeline: blocked-here time IS
                    # input starvation.  Once the session is stopped AND
                    # the pipe stays dry past a grace window, stop waiting
                    # — the pump is wedged on something a teardown already
                    # gave up on (a graceful grow drain keeps yielding, so
                    # it never trips this).
                    t0 = time.monotonic()
                    while True:
                        try:
                            kind, item = self._q.get(timeout=0.5)
                            break
                        except queue.Empty:
                            if (self._should_stop is not None
                                    and self._should_stop()
                                    and time.monotonic() - t0 > 5.0):
                                from ray_tpu.data.ingest.executor import (
                                    IngestAborted,
                                )

                                raise IngestAborted(
                                    "session stopped while the prefetch "
                                    "queue was starved")
                    starved = time.monotonic() - t0
                    ingest_metrics.STARVED_SECONDS.inc(starved)
                    w1 = time.time()
                    _profiler_record("data_wait", w1 - starved, w1)
                ingest_metrics.PREFETCH_OCCUPANCY.set(self._q.qsize())
                if kind == "end":
                    return
                if kind == "error":
                    raise item
                yield item
        finally:
            self.close()

    def close(self) -> None:
        self._stop.set()
        # Drain so a pump blocked on a full queue observes the stop at its
        # next timeout tick and exits.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


class DeviceBatchIterator:
    """Double-buffered host->device transfer over a batch iterator.

    Yields batch N only after batch N+1's transfer has been *dispatched*
    — with JAX's async dispatch the copy overlaps the consumer's step on
    batch N.  ``sharding`` (a NamedSharding, e.g. from
    ``ray_tpu.parallel.mesh.batch_sharding``) places each numeric column
    directly into the step's layout; without one, arrays go to the
    default device.  Non-numeric columns pass through on host.
    """

    def __init__(self, batches: Iterable[Dict[str, Any]], *,
                 sharding: Any = None):
        self._src = batches
        self._sharding = sharding

    def _transfer(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu._private import jax_compat

        w0 = time.time()
        try:
            with tracing.span("data.prefetch"):
                last: Optional[BaseException] = None
                for _attempt in range(2):
                    try:
                        fault_injection.check("data_ingest_prefetch")
                        return jax_compat.device_put_batch(
                            batch, sharding=self._sharding,
                            transfer_src="ingest_prefetch")
                    except WorkerCrashedError as e:
                        last = e
                raise last  # type: ignore[misc]
        finally:
            _profiler_record("h2d", w0, time.time())

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        it = iter(self._src)
        try:
            cur = self._transfer(next(it))
        except StopIteration:
            return
        for nxt in it:
            nxt_dev = self._transfer(nxt)  # dispatch N+1 before yielding N
            yield cur
            cur = nxt_dev
        yield cur
