"""Distributed data exchange: shuffle/sort/groupby as SCHEDULED TASKS.

TPU-native analogue of the reference's push-based shuffle (ref:
python/ray/data/_internal/planner/exchange/push_based_shuffle_task_scheduler.py
and sort_task_spec.py): a MAP stage partitions every input block into P
partition blocks (hash of the key, range against sampled boundaries, or
random), and a REDUCE stage merges/sorts/aggregates each partition — all as
tasks over the object store, so block data never concatenates on the
driver.  The driver holds only ObjectRefs and the tiny sample/count
metadata; any dataset that fits the cluster's stores (not the driver heap)
exchanges fine, and on worker-node clusters partition blocks move node-to-
node over the object plane.

Global (key-less) aggregations reduce per-block PARTIAL STATES (sum/count/
min/max/M2) combined on the driver — one small dict per block.  quantile/
unique have no bounded partial: they gather the single COLUMN (documented:
bounded by column bytes, not dataset bytes).
"""

from __future__ import annotations

from typing import Any, Iterator, List

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block, BlockAccessor, block_from_rows, concat_blocks

#: Cap on reduce partitions (P) — below it, P tracks the input block count.
#: Default; the live value comes from the config flag so operators can
#: raise it for wide clusters (RAY_TPU_DATA_MAX_PARTITIONS).
MAX_PARTITIONS = 32
#: Map/reduce tasks in flight (same backpressure role as executor.MAX_IN_FLIGHT).
MAX_IN_FLIGHT = 8


def _num_partitions(n_blocks: int) -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    cap = getattr(GLOBAL_CONFIG, "data_max_partitions", MAX_PARTITIONS)
    return max(1, min(n_blocks, cap))


# ----------------------------------------------------------------- map tasks
@ray_tpu.remote
def _sample_keys(blk: Block, key: str, k: int):
    vals = block_mod.column_to_numpy(blk, key)
    if len(vals) <= k:
        return np.asarray(vals)
    idx = np.linspace(0, len(vals) - 1, k).astype(np.int64)
    return np.asarray(vals)[idx]


@ray_tpu.remote
def _count_rows(blk: Block) -> int:
    return BlockAccessor(blk).num_rows()


def _take(acc: BlockAccessor, idx) -> Block:
    """take() with a typed-empty guard: an empty python list becomes a
    null-typed arrow array, which string columns cannot take() from."""
    if len(idx) == 0:
        return acc.slice(0, 0)
    return acc.take(list(map(int, idx)))


def _partition_hash(blk: Block, key: str, p: int):
    """Bucket rows by a hash that is STABLE ACROSS PROCESSES (python's str
    hash is randomized per interpreter; map tasks may run on different
    nodes, and all rows of one key must land in one partition)."""
    acc = BlockAccessor(blk)
    vals = np.asarray(block_mod.column_to_numpy(blk, key))
    if vals.dtype.kind in "iub":
        buckets = (vals.astype(np.int64) % p + p) % p
    elif vals.dtype.kind == "f":
        # hash() of numeric values is NOT randomized — stable everywhere —
        # EXCEPT NaN, whose hash is id-based since 3.10: pin all NaNs to
        # bucket 0 so they stay one group across processes.
        buckets = np.asarray([0 if v != v else abs(hash(float(v))) % p
                              for v in vals])
    else:
        buckets = np.asarray(
            [int.from_bytes(str(v).encode()[-8:].rjust(8, b"\0"), "little") % p
             for v in vals])
    return [_take(acc, np.nonzero(buckets == i)[0]) for i in range(p)]


def _partition_range(blk: Block, key: str, bounds: np.ndarray):
    acc = BlockAccessor(blk)
    vals = block_mod.column_to_numpy(blk, key)
    buckets = np.searchsorted(bounds, vals, side="right")
    return [_take(acc, np.nonzero(buckets == i)[0])
            for i in range(len(bounds) + 1)]


def _partition_random(blk: Block, p: int, seed):
    acc = BlockAccessor(blk)
    n = acc.num_rows()
    rng = np.random.default_rng(seed)
    buckets = rng.integers(0, p, n)
    return [_take(acc, np.nonzero(buckets == i)[0]) for i in range(p)]


# -------------------------------------------------------------- reduce tasks
def _merge(parts) -> Block:
    nonempty = [b for b in parts if BlockAccessor(b).num_rows() > 0]
    if nonempty:
        return concat_blocks(nonempty)
    # All-empty partition: keep a SCHEMA-BEARING empty block (concat_blocks
    # of nothing degrades to a schema-less table, which breaks group_by).
    return parts[0]


@ray_tpu.remote
def _reduce_sort(key: str, descending: bool, *parts) -> Block:
    import pyarrow.compute as pc

    combined = _merge(parts)
    idx = pc.sort_indices(
        combined, sort_keys=[(key, "descending" if descending else "ascending")])
    return combined.take(idx)


@ray_tpu.remote
def _reduce_shuffle(seed, *parts) -> Block:
    combined = _merge(parts)
    n = BlockAccessor(combined).num_rows()
    rng = np.random.default_rng(seed)
    return BlockAccessor(combined).take(list(map(int, rng.permutation(n))))


@ray_tpu.remote
def _reduce_concat(*parts) -> Block:
    return _merge(parts)


@ray_tpu.remote
def _reduce_aggregate(op, *parts) -> Block:
    from ray_tpu.data.executor import _aggregate

    return _aggregate(_merge(parts), op)


@ray_tpu.remote
def _reduce_map_groups(op, *parts) -> Block:
    from ray_tpu.data.executor import _map_groups

    return _map_groups(_merge(parts), op)


@ray_tpu.remote
def _slice_block(blk: Block, start: int, stop: int) -> Block:
    return BlockAccessor(blk).slice(start, stop)


# ------------------------------------------------------------- orchestration
def _bounded(tasks: List[Any]) -> Iterator[Any]:
    """Drain already-submitted reduce tasks in completion order."""
    pending = list(tasks)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=60.0)
        yield from ready


@ray_tpu.remote
def _partition_range_task(blk, key, bounds):
    return tuple(_partition_range(blk, key, bounds))


@ray_tpu.remote
def _partition_hash_task(blk, key, p):
    return tuple(_partition_hash(blk, key, p))


@ray_tpu.remote
def _partition_random_task(blk, p, sub):
    return tuple(_partition_random(blk, p, sub))


def _map_partitions(refs: List[Any], task_fn, p: int,
                    args_for) -> List[List[Any]]:
    """Run the map stage with bounded in-flight tasks; returns
    per-partition lists of partition-block refs (transposed).
    ``args_for(i)`` supplies the extra task args for input block i (one
    shared remote function — no per-block closures to pickle).  p == 1
    passes blocks through unsplit (a single partition IS the block)."""
    out: List[List[Any]] = [[] for _ in range(p)]
    if p == 1:
        out[0] = list(refs)
        return out
    pending = []
    for i, r in enumerate(refs):
        res = task_fn.options(num_returns=p).remote(r, *args_for(i))
        for j in range(p):
            out[j].append(res[j])
        pending.append(res[0])
        while len(pending) >= MAX_IN_FLIGHT:
            _, pending = ray_tpu.wait(pending, num_returns=1, timeout=60.0)
    return out


def sorted_exchange(refs: List[Any], key: str, descending: bool) -> Iterator[Any]:
    """Sample -> range-partition -> per-partition sort (ref:
    sort_task_spec.py SortTaskSpec.sample_boundaries)."""
    p = _num_partitions(len(refs))
    samples = ray_tpu.get([_sample_keys.remote(r, key, 32) for r in refs])
    allsamp = np.sort(np.concatenate([np.asarray(s) for s in samples]))
    if p > 1 and len(allsamp):
        idx = (np.arange(1, p) * len(allsamp)) // p
        bounds = allsamp[idx]
    else:
        bounds = np.asarray([])

    parts = _map_partitions(refs, _partition_range_task, len(bounds) + 1,
                            lambda i: (key, bounds))
    reducers = [_reduce_sort.remote(key, descending, *pp) for pp in parts]
    if descending:
        reducers = list(reversed(reducers))
    # Yield IN PARTITION ORDER: output blocks are globally sorted.
    yield from reducers


def shuffle_exchange(refs: List[Any], seed) -> Iterator[Any]:
    p = _num_partitions(len(refs))
    # Distinct per-block sub-seeds, fixed at submission time: a seeded
    # shuffle is deterministic regardless of task placement.
    parts = _map_partitions(
        refs, _partition_random_task, p,
        lambda i: (p, None if seed is None else seed + i * 7919))
    reducers = [
        _reduce_shuffle.remote(None if seed is None else seed + 104729 + j, *pp)
        for j, pp in enumerate(parts)]
    # Partition order, not completion order: a SEEDED shuffle must be
    # bit-deterministic end to end.
    yield from reducers


def repartition_exchange(refs: List[Any], k: int) -> Iterator[Any]:
    """Order-preserving repartition into k blocks via counted slices —
    reduce tasks pull exactly the ranges they need."""
    k = max(1, k)
    counts = ray_tpu.get([_count_rows.remote(r) for r in refs])
    total = int(sum(counts))
    size = max(1, (total + k - 1) // k)
    offsets = np.cumsum([0] + list(counts))
    reducers = []
    for j in range(k):
        lo, hi = j * size, min((j + 1) * size, total)
        if lo >= hi:
            break
        pieces = []
        for bi, r in enumerate(refs):
            b_lo, b_hi = int(offsets[bi]), int(offsets[bi + 1])
            s, e = max(lo, b_lo), min(hi, b_hi)
            if s < e:
                pieces.append(_slice_block.remote(r, s - b_lo, e - b_lo))
        reducers.append(_reduce_concat.remote(*pieces))
    yield from reducers


def hash_exchange(refs: List[Any], op, reduce_kind: str) -> Iterator[Any]:
    """Hash-partition on the key; aggregate/map_groups per partition (all
    rows of one key land in one partition, so per-partition reduction is
    exact)."""
    p = _num_partitions(len(refs))
    key = op.key
    parts = _map_partitions(refs, _partition_hash_task, p,
                            lambda i: (key, p))
    reducer = _reduce_aggregate if reduce_kind == "aggregate" \
        else _reduce_map_groups
    reducers = [reducer.remote(op, *pp) for pp in parts]
    yield from _bounded(reducers)


# ----------------------------------------------------- global (key-less) agg
@ray_tpu.remote
def _partial_states(blk: Block, specs) -> list:
    """One bounded partial state per aggregation spec."""
    acc = BlockAccessor(blk)
    out = []
    for col, fn in specs:
        if fn in ("count", "*count"):
            if col == "*":
                out.append(("count", acc.num_rows()))
            else:
                out.append(("count", len(block_mod.column_to_numpy(blk, col))))
            continue
        vals = np.asarray(block_mod.column_to_numpy(blk, col))
        if fn in ("quantile", "unique"):
            # No bounded partial: ship the COLUMN (not the block).
            out.append(("column", vals))
        elif fn == "sum":
            out.append(("sum", vals.sum() if len(vals) else 0.0))
        elif fn == "min":
            out.append(("min", vals.min() if len(vals) else None))
        elif fn == "max":
            out.append(("max", vals.max() if len(vals) else None))
        elif fn in ("mean", "std"):
            out.append(("moments", (len(vals), float(vals.sum()),
                                    float((vals.astype(np.float64) ** 2).sum()))))
        else:
            raise ValueError(f"unknown aggregation {fn!r}")
    return out


def global_aggregate(refs: List[Any], op) -> Block:
    """Combine per-block partials into the single result row."""
    from ray_tpu.data.executor import _normalize_agg

    specs, metas = [], []
    for agg in op.aggs:
        col, fn, spec = _normalize_agg(agg)
        specs.append((col, fn))
        metas.append((col, fn, spec))
    partials = ray_tpu.get([_partial_states.remote(r, specs) for r in refs])

    row = {}
    for i, (col, fn, spec) in enumerate(metas):
        states = [p[i] for p in partials]
        name = spec.output_name if spec is not None else f"{fn}({col})"
        if fn == "count" or col == "*":
            row[name] = int(sum(s[1] for s in states))
        elif fn == "sum":
            row[name] = sum(s[1] for s in states)
        elif fn == "min":
            vals = [s[1] for s in states if s[1] is not None]
            row[name] = min(vals) if vals else None
        elif fn == "max":
            vals = [s[1] for s in states if s[1] is not None]
            row[name] = max(vals) if vals else None
        elif fn in ("mean", "std"):
            n = sum(s[1][0] for s in states)
            tot = sum(s[1][1] for s in states)
            sq = sum(s[1][2] for s in states)
            if fn == "mean":
                row[name] = tot / n if n else None
            else:
                ddof = getattr(spec, "ddof", 1)
                var = (sq - tot * tot / n) / max(1, n - ddof) if n else None
                row[name] = float(np.sqrt(var)) if var is not None else None
        else:  # quantile / unique on the gathered column
            column = np.concatenate([np.asarray(s[1]) for s in states]) \
                if states else np.asarray([])
            if fn == "quantile":
                row[name] = float(np.quantile(column, getattr(spec, "q", 0.5)))
            else:
                row[name] = sorted(set(column.tolist()))
    return block_from_rows([row])
