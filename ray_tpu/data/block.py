"""Blocks — the unit of data movement (ref: python/ray/data/block.py:
Block = Arrow table; BlockAccessor wraps format-specific access).

Canonical block format is a pyarrow.Table (zero-copy into the object store's
buffer tier); batches convert to "numpy" (dict of arrays — the TPU-friendly
form fed to jax), "pandas", or "pyarrow" on demand.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Batch = Union[Dict[str, np.ndarray], "pa.Table", Any]


def block_from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return pa.table({})
    # Schema = union of keys across ALL rows (first-seen order); rows missing
    # a column contribute nulls.  Deriving it from rows[0] alone silently
    # drops late-appearing columns.
    cols: Dict[str, list] = {}
    for row in rows:
        for k in row:
            if k not in cols:
                cols[k] = []
    for row in rows:
        for k in cols:
            cols[k].append(row.get(k))

    def to_column(vals: list):
        # Ragged/variable-length cells (or None mixed with lists) become an
        # arrow LIST column — np.asarray would raise on inhomogeneous rows.
        if any(isinstance(v, (list, tuple)) for v in vals):
            try:
                arr = np.asarray(vals)
                if arr.dtype != object:
                    return arr  # rectangular: keep the tensor-column path
            except ValueError:
                pass
            return pa.array([None if v is None else list(v)
                             if isinstance(v, (list, tuple)) else [v]
                             for v in vals])
        return np.asarray(vals)

    return block_from_batch({k: to_column(v) for k, v in cols.items()})


def block_from_batch(batch: Batch) -> Block:
    if isinstance(batch, pa.Table):
        return batch
    if hasattr(batch, "to_dict") and type(batch).__module__.startswith("pandas"):
        return pa.Table.from_pandas(batch, preserve_index=False)
    if isinstance(batch, dict):
        arrays, fields = [], []
        for k, v in batch.items():
            arr, field = _to_arrow_array(k, v)
            arrays.append(arr)
            fields.append(field)
        return pa.table(arrays, schema=pa.schema(fields))
    raise TypeError(f"Cannot make a block from {type(batch)}")


#: Field metadata key holding the per-row tensor shape for ndim>=3 columns
#: (legacy encoding — data written before ArrowTensorType still reads).
_SHAPE_META = b"ray_tpu.tensor_shape"


class ArrowTensorType(pa.ExtensionType):
    """Fixed-shape tensor column type: each row is an ndarray of ``shape``.

    A REAL Arrow extension type (ref: python/ray/air/util/tensor_extensions/
    arrow.py ArrowTensorType) — the shape rides in the type itself and
    survives parquet/IPC/exchange without side-channel field metadata.
    Storage: fixed-size-list of the flattened values."""

    EXT_NAME = "ray_tpu.tensor"

    def __init__(self, shape: Tuple[int, ...], value_type: pa.DataType):
        self._shape = tuple(int(s) for s in shape)
        size = 1
        for s in self._shape:
            size *= s
        super().__init__(pa.list_(value_type, size), self.EXT_NAME)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def value_type(self) -> pa.DataType:
        return self.storage_type.value_type

    def __arrow_ext_serialize__(self) -> bytes:
        import json

        return json.dumps(list(self._shape)).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        import json

        return cls(tuple(json.loads(serialized.decode())),
                   storage_type.value_type)

    @classmethod
    def from_numpy(cls, arr: np.ndarray) -> pa.ExtensionArray:
        # Explicit row width: reshape(len, -1) is a ValueError on ZERO rows.
        width = int(np.prod(arr.shape[1:], dtype=np.int64))
        flat = np.ascontiguousarray(arr).reshape(len(arr), width)
        storage = pa.FixedSizeListArray.from_arrays(
            pa.array(flat.ravel()), width)
        return pa.ExtensionArray.from_storage(
            cls(arr.shape[1:], storage.type.value_type), storage)


# Registration is process-global and idempotent per name; needed so parquet/
# IPC readers reconstruct the extension type instead of its storage type.
try:
    pa.register_extension_type(ArrowTensorType((1,), pa.int64()))
except pa.ArrowKeyError:
    pass  # already registered (module reload)


def _to_arrow_array(name: str, values) -> Tuple[pa.Array, pa.Field]:
    if isinstance(values, (pa.Array, pa.ChunkedArray)):
        return values, pa.field(name, values.type)
    arr = np.asarray(values)
    if arr.ndim > 1:
        pa_arr = ArrowTensorType.from_numpy(arr)
        return pa_arr, pa.field(name, pa_arr.type)
    pa_arr = pa.array(arr)
    return pa_arr, pa.field(name, pa_arr.type)


class BlockAccessor:
    """(ref: data/block.py BlockAccessor)"""

    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def to_batch(self, batch_format: str = "numpy") -> Batch:
        if batch_format in ("numpy", "default"):
            return {
                name: column_to_numpy(self.block, name)
                for name in self.block.column_names
            }
        if batch_format == "pandas":
            return self.block.to_pandas()
        if batch_format == "pyarrow":
            return self.block
        raise ValueError(f"Unknown batch_format: {batch_format}")

    def iter_rows(self) -> Iterable[Dict[str, Any]]:
        cols = {name: column_to_numpy(self.block, name)
                for name in self.block.column_names}
        for i in range(self.block.num_rows):
            yield {k: v[i] for k, v in cols.items()}

    def take(self, indices: List[int]) -> Block:
        return self.block.take(pa.array(indices))


def column_to_numpy(block: Block, name: str) -> np.ndarray:
    col = block.column(name)
    if isinstance(col.type, ArrowTensorType):
        combined = col.combine_chunks()
        flat = combined.storage.values.to_numpy(zero_copy_only=False)
        return flat.reshape((len(col),) + col.type.shape)
    if isinstance(col.type, pa.FixedSizeListType):
        # Legacy tensor encoding (pre-ArrowTensorType): shape from field
        # metadata; plain fixed-size-list columns unroll as (N, list_size).
        combined = col.combine_chunks()
        flat = combined.values.to_numpy(zero_copy_only=False)
        field = block.schema.field(name)
        shape: Tuple[int, ...] = (col.type.list_size,)
        if field.metadata and _SHAPE_META in field.metadata:
            shape = tuple(int(s) for s in field.metadata[_SHAPE_META].decode().split(","))
        return flat.reshape((len(col),) + shape)
    return col.to_numpy(zero_copy_only=False)


def rebatch(block_iter: Iterable[Block], batch_size: Optional[int],
            batch_format: str = "numpy") -> Iterable[Batch]:
    """Re-slice a stream of blocks into exact-size batches (shared by
    Dataset.iter_batches and DataIterator.iter_batches)."""
    carry: List[Block] = []
    carry_rows = 0
    for block in block_iter:
        if block.num_rows == 0:
            continue
        if batch_size is None:
            yield BlockAccessor(block).to_batch(batch_format)
            continue
        carry.append(block)
        carry_rows += block.num_rows
        while carry_rows >= batch_size:
            merged = concat_blocks(carry)
            acc = BlockAccessor(merged)
            yield BlockAccessor(acc.slice(0, batch_size)).to_batch(batch_format)
            rest = acc.slice(batch_size, acc.num_rows())
            carry = [rest] if rest.num_rows > 0 else []
            carry_rows = acc.num_rows() - batch_size
    if carry_rows:
        yield BlockAccessor(concat_blocks(carry)).to_batch(batch_format)


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b.num_rows > 0]
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks)
