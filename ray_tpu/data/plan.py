"""Logical plan: operator DAG + optimizer rules.

(ref: python/ray/data/_internal/logical/operators/ — Read, MapBatches, ...;
optimizer rules in _internal/logical/rules/ and optimizers.py; planner in
_internal/planner/planner.py).  A Dataset is a chain of logical ops; the
optimizer fuses adjacent per-block transforms into one task (operator fusion
— the single most important Data optimization: one object-store round trip
per block instead of one per op).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOp:
    name: str = "op"

    def __init__(self, input_op: Optional["LogicalOp"] = None):
        self.input_op = input_op

    def chain(self) -> List["LogicalOp"]:
        ops: List[LogicalOp] = []
        op: Optional[LogicalOp] = self
        while op is not None:
            ops.append(op)
            op = op.input_op
        return list(reversed(ops))


class Read(LogicalOp):
    name = "Read"

    def __init__(self, read_tasks: List[Callable[[], Any]], schema_hint=None):
        super().__init__(None)
        self.read_tasks = read_tasks
        self.schema_hint = schema_hint


class InputData(LogicalOp):
    name = "InputData"

    def __init__(self, blocks: List[Any]):
        super().__init__(None)
        self.blocks = blocks


@dataclass
class ComputeStrategy:
    """TaskPool (default) vs ActorPool (stateful, e.g. model inference on
    TPU actors) (ref: task_pool_map_operator.py / actor_pool_map_operator.py)."""

    kind: str = "tasks"
    pool_size: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    #: Autoscaling ceiling for actor pools (ref: data/_internal/execution/
    #: autoscaler/ actor-pool autoscaling): the executor grows the pool from
    #: pool_size up to max_size while the op is backlogged.
    max_size: int = 1


class ActorPoolStrategy(ComputeStrategy):
    def __init__(self, size: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 min_size: int = 1, max_size: Optional[int] = None):
        if size is not None:
            min_size = max_size = size
        if min_size < 1:
            raise ValueError(f"min_size must be >= 1, got {min_size}")
        if max_size is not None and max_size < min_size:
            raise ValueError(
                f"max_size ({max_size}) must be >= min_size ({min_size})")
        super().__init__(kind="actors", pool_size=min_size,
                         resources=resources or {},
                         max_size=max(max_size or min_size, min_size))


class AbstractMap(LogicalOp):
    """Per-block transform: block -> block."""

    def __init__(self, input_op: LogicalOp, fn: Callable, compute: Optional[ComputeStrategy] = None,
                 fn_constructor: Optional[Callable] = None, name: str = "Map"):
        super().__init__(input_op)
        self.fn = fn
        self.compute = compute or ComputeStrategy()
        self.fn_constructor = fn_constructor
        self.name = name


class MapBatches(AbstractMap):
    def __init__(self, input_op, fn, batch_size: Optional[int] = None,
                 batch_format: str = "numpy", compute=None, fn_constructor=None):
        super().__init__(input_op, fn, compute, fn_constructor, name="MapBatches")
        self.batch_size = batch_size
        self.batch_format = batch_format


class MapRows(AbstractMap):
    def __init__(self, input_op, fn, compute=None):
        super().__init__(input_op, fn, compute, name="Map")


class Filter(AbstractMap):
    def __init__(self, input_op, fn, compute=None):
        super().__init__(input_op, fn, compute, name="Filter")


class FlatMap(AbstractMap):
    def __init__(self, input_op, fn, compute=None):
        super().__init__(input_op, fn, compute, name="FlatMap")


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, input_op, limit: int):
        super().__init__(input_op)
        self.limit = limit


class Repartition(LogicalOp):
    name = "Repartition"

    def __init__(self, input_op, num_blocks: int):
        super().__init__(input_op)
        self.num_blocks = num_blocks


class RandomShuffle(LogicalOp):
    name = "RandomShuffle"

    def __init__(self, input_op, seed: Optional[int] = None):
        super().__init__(input_op)
        self.seed = seed


class Sort(LogicalOp):
    name = "Sort"

    def __init__(self, input_op, key: str, descending: bool = False):
        super().__init__(input_op)
        self.key = key
        self.descending = descending


class Union(LogicalOp):
    name = "Union"

    def __init__(self, input_op, others: List[LogicalOp]):
        super().__init__(input_op)
        self.others = others


class Zip(LogicalOp):
    """Row-aligned column concat with another plan (ref: logical/operators/
    zip_operator.py).  The right side materializes at execution; the left
    streams through, keeping its block boundaries."""

    def __init__(self, input_op, other: LogicalOp):
        super().__init__(input_op)
        self.other = other


class Aggregate(LogicalOp):
    name = "Aggregate"

    def __init__(self, input_op, key: Optional[str], aggs: List[Any]):
        super().__init__(input_op)
        self.key = key
        #: mixed list of (column, fn-name) tuples and data.aggregate
        #: AggregateFn specs; the executor normalizes.
        self.aggs = aggs


class MapGroups(LogicalOp):
    """Apply a UDF per group (ref: grouped_data.py:93 map_groups — sorts by
    key, slices group boundaries, maps each group batch)."""

    name = "MapGroups"

    def __init__(self, input_op, key: Optional[str], fn, batch_format: str = "numpy"):
        super().__init__(input_op)
        self.key = key
        self.fn = fn
        self.batch_format = batch_format


def fuse_maps(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Fuse adjacent task-pool maps (ref: rules/operator_fusion.py).

    Actor-pool maps are never fused into task maps (different executors), and
    MapBatches with different batch formats keep their own batching.
    """
    from ray_tpu.data.executor import make_block_transform

    fused: List[LogicalOp] = []
    for op in ops:
        if (
            isinstance(op, AbstractMap)
            and fused
            and isinstance(fused[-1], AbstractMap)
            and fused[-1].compute.kind == "tasks"
            and op.compute.kind == "tasks"
            and fused[-1].fn_constructor is None
            and op.fn_constructor is None
        ):
            prev = fused.pop()
            f1 = make_block_transform(prev)
            f2 = make_block_transform(op)

            def composed(block, _f1=f1, _f2=f2):
                return _f2(_f1(block))

            merged = AbstractMap(prev.input_op, composed,
                                 name=f"{prev.name}->{op.name}")
            merged._pre_transformed = True
            fused.append(merged)
        else:
            fused.append(op)
    return fused
