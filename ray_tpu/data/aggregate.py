"""Aggregation specs (ref: python/ray/data/aggregate.py — AggregateFn and
the named aggregations Count/Sum/Min/Max/Mean/Std/Quantile/Unique used by
``Dataset.aggregate`` and ``GroupedData.aggregate``).

Each spec is declarative: a column + a function name the executor lowers
either to a numpy reduction (global aggregate over the combined block) or an
arrow ``group_by().aggregate`` kernel (grouped path).
"""

from __future__ import annotations

from typing import Any, Optional


class AggregateFn:
    """Base spec: subclass instances name a (column, function) pair."""

    fn_name: str = ""

    def __init__(self, on: Optional[str] = None, alias_name: Optional[str] = None):
        self.on = on
        self.alias_name = alias_name

    @property
    def output_name(self) -> str:
        if self.alias_name:
            return self.alias_name
        if self.on is None:
            return f"{self.fn_name}()"
        return f"{self.fn_name}({self.on})"

    def __repr__(self) -> str:
        return f"{type(self).__name__}(on={self.on!r})"


class Count(AggregateFn):
    fn_name = "count"


class Sum(AggregateFn):
    fn_name = "sum"


class Min(AggregateFn):
    fn_name = "min"


class Max(AggregateFn):
    fn_name = "max"


class Mean(AggregateFn):
    fn_name = "mean"


class Std(AggregateFn):
    """Sample standard deviation, ddof=1 by default (ref: aggregate.py Std)."""

    fn_name = "std"

    def __init__(self, on: Optional[str] = None, ddof: int = 1,
                 alias_name: Optional[str] = None):
        super().__init__(on, alias_name)
        self.ddof = ddof


class Quantile(AggregateFn):
    """Exact quantile over the combined column (global aggregates only —
    the grouped path has no exact streaming quantile kernel, matching the
    reference's sort-based implementation cost)."""

    fn_name = "quantile"

    def __init__(self, on: Optional[str] = None, q: float = 0.5,
                 alias_name: Optional[str] = None):
        super().__init__(on, alias_name)
        self.q = q


class Unique(AggregateFn):
    fn_name = "unique"
