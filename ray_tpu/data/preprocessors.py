"""Preprocessors: fit-on-dataset, transform-as-map_batches feature prep.

Counterpart of the reference's `ray.data.preprocessors`
(ref: python/ray/data/preprocessors/ — scaler.py StandardScaler/MinMaxScaler,
encoder.py LabelEncoder/OneHotEncoder, imputer.py SimpleImputer,
concatenator.py Concatenator, chain.py Chain): `fit()` computes statistics
with dataset aggregates, `transform()` appends a `map_batches` stage so the
work runs inside the streaming executor — TPU angle: `Concatenator` produces
the single dense feature matrix a jax train loop wants.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Preprocessor:
    """fit/transform contract (ref: preprocessor.py Preprocessor)."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self._transform_batch)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Direct batch application (serving path)."""
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return self._transform_batch(dict(batch))

    # overridables
    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds) -> None:
        pass

    def _transform_batch(self, batch):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (ref: preprocessors/scaler.py)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        for col in self.columns:
            mean = ds.mean(col)
            sq = ds.map_batches(
                lambda b, c=col: {"_sq": np.asarray(b[c], np.float64) ** 2})
            var = sq.mean("_sq") - mean ** 2
            self.stats_[col] = (mean, float(np.sqrt(max(var, 0.0))))

    def _transform_batch(self, batch):
        for col in self.columns:
            mean, std = self.stats_[col]
            batch[col] = (np.asarray(batch[col], np.float64) - mean) / (std or 1.0)
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        for col in self.columns:
            self.stats_[col] = (ds.min(col), ds.max(col))

    def _transform_batch(self, batch):
        for col in self.columns:
            lo, hi = self.stats_[col]
            span = (hi - lo) or 1.0
            batch[col] = (np.asarray(batch[col], np.float64) - lo) / span
        return batch


class LabelEncoder(Preprocessor):
    """Categorical column -> integer codes (ref: preprocessors/encoder.py)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: List = []

    def _fit(self, ds) -> None:
        values = set()
        for batch in ds.iter_batches(batch_format="numpy"):
            values.update(np.asarray(batch[self.label_column]).tolist())
        self.classes_ = sorted(values)
        self._index = {v: i for i, v in enumerate(self.classes_)}

    def _transform_batch(self, batch):
        col = np.asarray(batch[self.label_column])
        batch[self.label_column] = np.asarray(
            [self._index[v] for v in col.tolist()], np.int64)
        return batch


class OneHotEncoder(Preprocessor):
    """Each category becomes a 0/1 column `col_value`."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.categories_: Dict[str, List] = {}

    def _fit(self, ds) -> None:
        for col in self.columns:
            values = set()
            for batch in ds.iter_batches(batch_format="numpy"):
                values.update(np.asarray(batch[col]).tolist())
            self.categories_[col] = sorted(values)

    def _transform_batch(self, batch):
        for col in self.columns:
            data = np.asarray(batch.pop(col))
            for cat in self.categories_[col]:
                batch[f"{col}_{cat}"] = (data == cat).astype(np.int8)
        return batch


class SimpleImputer(Preprocessor):
    """Fill NaNs with the column mean (or a constant)."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Optional[float] = None):
        if strategy not in ("mean", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.fills_: Dict[str, float] = {}

    def _needs_fit(self) -> bool:
        return self.strategy == "mean"

    def _fit(self, ds) -> None:
        if self.strategy != "mean":
            return
        for col in self.columns:
            total = n = 0.0
            for batch in ds.iter_batches(batch_format="numpy"):
                arr = np.asarray(batch[col], np.float64)
                mask = ~np.isnan(arr)
                total += float(arr[mask].sum())
                n += float(mask.sum())
            self.fills_[col] = total / n if n else 0.0

    def _transform_batch(self, batch):
        for col in self.columns:
            arr = np.asarray(batch[col], np.float64)
            fill = (self.fill_value if self.strategy == "constant"
                    else self.fills_[col])
            batch[col] = np.where(np.isnan(arr), fill, arr)
        return batch


class Concatenator(Preprocessor):
    """Pack columns into one dense matrix column — the shape a jax/pjit train
    step consumes (ref: preprocessors/concatenator.py)."""

    def __init__(self, columns: List[str], output_column_name: str = "concat_out",
                 dtype=np.float32):
        self.columns = columns
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch):
        mats = []
        for col in self.columns:
            arr = np.asarray(batch.pop(col))
            mats.append(arr[:, None] if arr.ndim == 1 else arr)
        batch[self.output_column_name] = np.concatenate(
            mats, axis=1).astype(self.dtype)
        return batch


class Chain(Preprocessor):
    """Sequential composition (ref: preprocessors/chain.py)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = stages

    def _needs_fit(self) -> bool:
        return any(s._needs_fit() for s in self.stages)

    def fit(self, ds) -> "Chain":
        for stage in self.stages:
            if stage._needs_fit():
                stage.fit(ds)
            ds = stage.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for stage in self.stages:
            ds = stage.transform(ds)
        return ds

    def transform_batch(self, batch):
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch
