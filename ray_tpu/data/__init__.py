"""ray_tpu.data — streaming datasets (ref: python/ray/data/read_api.py)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import block_from_rows
from ray_tpu.data.dataset import DataIterator, Dataset
from ray_tpu.data.plan import ActorPoolStrategy, InputData, Read

DEFAULT_BLOCK_ROWS = 1000
_builtin_range = range  # captured before the read API shadows the name


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    """(ref: read_api.py:226 range) — column 'id'."""
    import pyarrow as pa

    if parallelism <= 0:
        parallelism = max(1, min(8, n // DEFAULT_BLOCK_ROWS or 1))
    size = (n + parallelism - 1) // parallelism if n else 0

    def make_task(start: int, end: int):
        def read():
            return pa.table({"id": np.arange(start, end, dtype=np.int64)})

        return read

    tasks = [make_task(i * size, min((i + 1) * size, n))
             for i in _builtin_range(parallelism) if i * size < n]
    if not tasks:
        tasks = [make_task(0, 0)]
    return Dataset(Read(tasks))


def from_items(items: List[Any]) -> Dataset:
    """(ref: read_api.py from_items)"""
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    blocks = []
    for start in _builtin_range(0, max(len(rows), 1), DEFAULT_BLOCK_ROWS):
        chunk = rows[start:start + DEFAULT_BLOCK_ROWS]
        if chunk or not blocks:
            blocks.append(block_from_rows(chunk))
    return Dataset(InputData(blocks))


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    from ray_tpu.data.block import block_from_batch

    return Dataset(InputData([block_from_batch({column: arr})]))


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    return Dataset(InputData([pa.Table.from_pandas(df, preserve_index=False)]))


def from_arrow(table) -> Dataset:
    return Dataset(InputData([table]))


def _expand_paths(paths, suffix: str) -> List[str]:
    """Expand dirs/globs/files into one globally sorted, deduplicated
    list.  Sorting the final list (not per input) makes the read-task
    order — and with it block order, splits and shard claims — a pure
    function of the matched file set: glob order is filesystem-dependent,
    and overlapping inputs (a dir plus a glob into it) must not read a
    file twice."""
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                f for f in _glob.glob(os.path.join(p, f"*{suffix}"))
                if os.path.isfile(f))
        elif "*" in p:
            out.extend(f for f in _glob.glob(p) if os.path.isfile(f))
        else:
            if not os.path.exists(p):
                raise FileNotFoundError(f"Path does not exist: {p}")
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No files matched {paths}")
    return sorted(dict.fromkeys(out))


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 shards_per_file: int = 1) -> Dataset:
    """(ref: read_api.py:602 read_parquet)

    ``shards_per_file > 1`` splits each file into that many row-group
    ranges (data/ingest/readers.py) — reader parallelism beyond file
    count, clamped to each file's actual row-group count."""
    import pyarrow.parquet as pq

    files = _expand_paths(paths, ".parquet")
    if shards_per_file > 1:
        from ray_tpu.data.ingest.readers import parquet_range_tasks

        tasks = [t for f in files
                 for t in parquet_range_tasks(f, shards_per_file,
                                              columns=columns)]
        return Dataset(Read(tasks))

    def make_task(f: str):
        def read():
            return pq.read_table(f, columns=columns)

        return read

    return Dataset(Read([make_task(f) for f in files]))


def read_csv(paths) -> Dataset:
    import pyarrow.csv as pacsv

    files = _expand_paths(paths, ".csv")

    def make_task(f: str):
        def read():
            return pacsv.read_csv(f)

        return read

    return Dataset(Read([make_task(f) for f in files]))


def read_json(paths) -> Dataset:
    import pyarrow.json as pajson

    files = _expand_paths(paths, ".json")

    def make_task(f: str):
        def read():
            return pajson.read_json(f)

        return read

    return Dataset(Read([make_task(f) for f in files]))


def read_numpy(paths) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def make_task(f: str):
        def read():
            from ray_tpu.data.block import block_from_batch

            return block_from_batch({"data": np.load(f)})

        return read

    return Dataset(Read([make_task(f) for f in files]))


def _looks_like_tfrecord(path: str) -> bool:
    """Cheap framing sanity check: the first 12-byte header's masked
    length-crc must verify (ref framing in data/tfrecords.py).  A 0-byte
    file is a valid EMPTY TFRecord shard (partitioned writers emit them)."""
    import struct

    from ray_tpu.data.tfrecords import _masked_crc

    try:
        with open(path, "rb") as f:
            header = f.read(12)
    except OSError:
        return False
    if len(header) == 0:
        return True
    if len(header) < 12:
        return False
    (length,) = struct.unpack("<Q", header[:8])
    (len_crc,) = struct.unpack("<I", header[8:])
    return _masked_crc(header[:8]) == len_crc and length < (1 << 40)


def read_tfrecords(paths, *, shards_per_file: int = 1) -> Dataset:
    """tf.train.Example TFRecord files -> one row per example (ref:
    read_api.py read_tfrecords; framing + protos in data/tfrecords.py,
    no TensorFlow dependency).  Directories match ``*.tfrecords`` AND
    TensorFlow's ``*.tfrecord`` convention, falling back to every file in
    the directory (TF shard names often have no extension at all — the
    reference reads all files regardless of suffix).

    ``shards_per_file > 1`` splits each file into that many byte ranges
    resynced at CRC-verified record boundaries (data/ingest/readers.py) —
    one giant shard no longer serializes the pipeline."""
    files: List[str] = []
    for p in ([paths] if isinstance(paths, str) else list(paths)):
        if os.path.isdir(p):
            matched = sorted(
                f for suffix in (".tfrecords", ".tfrecord")
                for f in _glob.glob(os.path.join(p, f"*{suffix}"))
                if os.path.isfile(f))
            if not matched:
                # Extensionless TF shard names: accept only files whose
                # first record header frames correctly — a stray README or
                # _SUCCESS marker otherwise surfaces later as a confusing
                # 'corrupt TFRecord length crc'.
                candidates = sorted(
                    os.path.join(p, f) for f in os.listdir(p)
                    if os.path.isfile(os.path.join(p, f)))
                matched = [f for f in candidates if _looks_like_tfrecord(f)]
                if candidates and not matched:
                    raise FileNotFoundError(
                        f"No *.tfrecord(s) files in {p} and none of its "
                        f"{len(candidates)} files frame as TFRecords "
                        f"(checked first-record length crc)")
                skipped = sorted(set(candidates) - set(matched))
                if skipped:
                    # Surface the skips: a junk marker (_SUCCESS/README) is
                    # expected, but a CORRUPT shard silently dropped here
                    # would be silent data loss.
                    import warnings

                    warnings.warn(
                        f"read_tfrecords: skipping {len(skipped)} file(s) in "
                        f"{p} that don't frame as TFRecords: "
                        f"{[os.path.basename(s) for s in skipped[:5]]}",
                        RuntimeWarning, stacklevel=2)
            files.extend(matched)
        else:
            files.extend(_expand_paths(p, ".tfrecords"))
    files = sorted(dict.fromkeys(files))  # same determinism as _expand_paths
    if not files:
        raise FileNotFoundError(f"No TFRecord files matched: {paths}")

    def make_task(f: str):
        def read():
            from ray_tpu.data.tfrecords import examples_to_block, read_records

            return examples_to_block(read_records(f))

        return read

    if shards_per_file > 1:
        from ray_tpu.data.ingest.readers import tfrecord_range_tasks

        return Dataset(Read([t for f in files
                             for t in tfrecord_range_tasks(
                                 f, shards_per_file)]))
    return Dataset(Read([make_task(f) for f in files]))


def read_text(paths) -> Dataset:
    """One row per line, column 'text' (ref: read_api.py read_text)."""
    files = _expand_paths(paths, ".txt")

    def make_task(f: str):
        def read():
            import pyarrow as pa

            with open(f, "r", errors="replace") as fh:
                lines = [ln.rstrip("\n") for ln in fh]
            # Explicit type: an empty file would otherwise infer a
            # null-typed column whose schema can't concat with real blocks.
            return pa.table({"text": pa.array(lines, pa.string())})

        return read

    return Dataset(Read([make_task(f) for f in files]))


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """Column 'bytes' (+ 'path') (ref: read_api.py read_binary_files)."""
    files = _expand_paths(paths, "")

    def make_task(f: str):
        def read():
            import pyarrow as pa

            with open(f, "rb") as fh:
                data = fh.read()
            cols = {"bytes": pa.array([data], pa.binary())}
            if include_paths:
                cols["path"] = pa.array([f])
            return pa.table(cols)

        return read

    return Dataset(Read([make_task(f) for f in files]))


def read_images(paths, *, size: Optional[tuple] = None,
                mode: str = "RGB",
                include_paths: bool = False) -> Dataset:
    """Column 'image' as HWC uint8 arrays (ref: read_api.py:781 read_images).

    All images are decoded to a single uniform (H, W, C): `mode` (default
    RGB) fixes C; `size=(H, W)` fixes H/W — when omitted, the first file's
    size is the target and other files are resized to it.  Uniformity is
    required for blocks to share a schema (fixed-size tensors batch
    cleanly onto the TPU anyway)."""
    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif")
    if isinstance(paths, str) and os.path.isdir(paths):
        files: List[str] = []
        for ext in exts:
            files.extend(sorted(
                f for f in _glob.glob(os.path.join(paths, f"*{ext}"))
                if os.path.isfile(f)))
        if not files:
            raise FileNotFoundError(f"No images under {paths}")
    else:
        files = _expand_paths(paths, "")

    if size is None:
        from PIL import Image

        with Image.open(files[0]) as probe:
            size = (probe.height, probe.width)

    def make_task(f: str):
        def read():
            from PIL import Image

            from ray_tpu.data.block import block_from_batch

            img = Image.open(f).convert(mode)
            if (img.height, img.width) != size:
                img = img.resize((size[1], size[0]))
            arr = np.asarray(img)
            if arr.ndim == 2:  # single-channel modes ("L"): keep HWC
                arr = arr[..., None]
            batch = {"image": arr[None, ...]}
            if include_paths:
                batch["path"] = np.asarray([f])
            return block_from_batch(batch)

        return read

    return Dataset(Read([make_task(f) for f in files]))


__all__ = [
    "ActorPoolStrategy", "DataIterator", "Dataset", "aggregate", "from_arrow",
    "from_items", "from_numpy", "from_pandas", "preprocessors", "range",
    "read_binary_files", "read_csv", "read_images", "read_json", "read_numpy",
    "read_parquet", "read_text", "read_tfrecords",
]

from ray_tpu.data import aggregate  # noqa: E402  (public submodule)
from ray_tpu.data import preprocessors  # noqa: E402  (public submodule)
