"""Dataset — lazy, streaming, distributed data (ref: python/ray/data/dataset.py:147).

Transforms append logical ops (plan.py); execution is streaming (executor.py)
and only happens on iteration/consumption, like the reference.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import executor as ex
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks
from ray_tpu.data.plan import (
    ActorPoolStrategy,
    Aggregate,
    ComputeStrategy,
    Filter,
    FlatMap,
    InputData,
    Limit,
    LogicalOp,
    MapBatches,
    MapRows,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    Union as UnionOp,
)


class Dataset:
    def __init__(self, op: LogicalOp):
        self._op = op

    # ------------------------------------------------------------ transforms
    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy", compute: Optional[ComputeStrategy] = None,
                    num_tpus: Optional[float] = None, concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (), **_compat) -> "Dataset":
        """(ref: dataset.py:397 map_batches — the batch-inference path).

        Stateful form: pass a class; it is constructed once per pool actor
        (TPU-pinned with num_tpus) and called per batch.
        """
        fn_constructor = None
        the_fn = fn
        if isinstance(fn, type):
            ctor_args = fn_constructor_args

            def fn_constructor():
                return fn(*ctor_args)

            def the_fn(batch, state):
                return state(batch)

            if compute is None:
                compute = ActorPoolStrategy(
                    size=concurrency or 1,
                    resources={"TPU": num_tpus} if num_tpus else {})
        elif num_tpus or (concurrency and concurrency > 1):
            compute = compute or ActorPoolStrategy(
                size=concurrency or 1,
                resources={"TPU": num_tpus} if num_tpus else {})
        return Dataset(MapBatches(self._op, the_fn, batch_size=batch_size,
                                  batch_format=batch_format, compute=compute,
                                  fn_constructor=fn_constructor))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return Dataset(MapRows(self._op, fn))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return Dataset(Filter(self._op, fn))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return Dataset(FlatMap(self._op, fn))

    def add_column(self, name: str, fn: Callable[[Dict], Any]) -> "Dataset":
        def add(row):
            row = dict(row)
            row[name] = fn(row)
            return row

        return Dataset(MapRows(self._op, add))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return Dataset(MapBatches(self._op, drop))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        return Dataset(MapBatches(self._op, select))

    def limit(self, n: int) -> "Dataset":
        return Dataset(Limit(self._op, n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(Repartition(self._op, num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(RandomShuffle(self._op, seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(Sort(self._op, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(UnionOp(self._op, [o._op for o in others]))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # ----------------------------------------------------------- consumption
    def iter_block_refs(self) -> Iterator[Any]:
        return ex.execute(self._op)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy") -> Iterator[Any]:
        """(ref: iterator.py:94 iter_batches) — streaming, overlaps execution."""
        from ray_tpu.data.block import rebatch

        blocks = (ray_tpu.get(ref) for ref in self.iter_block_refs())
        yield from rebatch(blocks, batch_size, batch_format)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self.iter_block_refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(
            BlockAccessor(ray_tpu.get(r)).num_rows() for r in self.iter_block_refs())

    def schema(self):
        for ref in self.iter_block_refs():
            block = ray_tpu.get(ref)
            if block.num_rows > 0 or block.schema.names:
                return block.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def materialize(self) -> "Dataset":
        """(ref: dataset.py materialize) — execute now, pin blocks."""
        refs = list(self.iter_block_refs())
        return Dataset(InputData(refs))

    def to_pandas(self):
        import pandas as pd

        blocks = [ray_tpu.get(r) for r in self.iter_block_refs()]
        merged = concat_blocks(blocks)
        return merged.to_pandas()

    def min(self, col: str):
        return self._simple_agg("min", col)

    def max(self, col: str):
        return self._simple_agg("max", col)

    def sum(self, col: str):
        return self._simple_agg("sum", col)

    def mean(self, col: str):
        return self._simple_agg("mean", col)

    def _simple_agg(self, fn: str, col: str):
        ds = Dataset(Aggregate(self._op, None, [(col, fn)]))
        rows = ds.take_all()
        return rows[0][f"{fn}({col})"]

    # --------------------------------------------------------------- splits
    def split(self, n: int) -> List["Dataset"]:
        """Materializing equal split (ref: dataset.py split)."""
        refs = list(self.iter_block_refs())
        blocks = [ray_tpu.get(r) for r in refs]
        merged = concat_blocks(blocks)
        acc = BlockAccessor(merged)
        total = acc.num_rows()
        size = (total + n - 1) // n if total else 0
        out = []
        for i in range(n):
            piece = acc.slice(min(i * size, total), min((i + 1) * size, total)) \
                if total else merged
            out.append(Dataset(InputData([ray_tpu.put(piece)])))
        return out

    def streaming_split(self, n: int, *, equal: bool = True) -> List["DataIterator"]:
        """Coordinated split for Train ingest (ref: StreamSplitDataIterator,
        _internal/iterator/stream_split_iterator.py:31): one shared execution,
        blocks dealt round-robin to n consumers."""
        coordinator = _SplitCoordinator(self, n, equal=equal)
        return [DataIterator(coordinator, i) for i in range(n)]

    # ---------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self.iter_block_refs()):
            block = ray_tpu.get(ref)
            if block.num_rows:
                pq.write_table(block, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str) -> None:
        import os

        import pyarrow.csv as pacsv

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self.iter_block_refs()):
            block = ray_tpu.get(ref)
            if block.num_rows:
                pacsv.write_csv(block, os.path.join(path, f"part-{i:05d}.csv"))

    def stats(self) -> str:
        return f"Dataset(plan={'->'.join(op.name for op in self._op.chain())})"

    def __repr__(self) -> str:
        return self.stats()


class GroupedData:
    """(ref: data/grouped_data.py)"""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def _agg(self, fn: str, col: str) -> Dataset:
        return Dataset(Aggregate(self._ds._op, self._key, [(col, fn)]))

    def sum(self, col: str) -> Dataset:
        return self._agg("sum", col)

    def min(self, col: str) -> Dataset:
        return self._agg("min", col)

    def max(self, col: str) -> Dataset:
        return self._agg("max", col)

    def mean(self, col: str) -> Dataset:
        return self._agg("mean", col)

    def count(self) -> Dataset:
        # Global count (key=None) counts rows of any column.
        col = self._key if self._key is not None else "*"
        return self._agg("count", col)


class _SplitCoordinator:
    """Single execution shared by n DataIterators (backpressured queues).

    equal=True deals row-slices so every consumer gets ~1/n of each block —
    a one-block dataset still feeds all n trainers (the reference's
    StreamSplitDataIterator guarantees balanced output for Train ingest).
    """

    def __init__(self, ds: Dataset, n: int, equal: bool = True):
        self.n = n
        self.equal = equal
        # Bounded for backpressure, but deep enough that a consumer lagging a
        # few blocks behind (consumers are normally concurrent trainer
        # workers) doesn't stall the shared pump.
        self.queues: List["queue.Queue"] = [queue.Queue(maxsize=64) for _ in range(n)]
        self._thread = threading.Thread(target=self._pump, args=(ds,), daemon=True)
        self._started = False
        self._lock = threading.Lock()

    def ensure_started(self):
        with self._lock:
            if not self._started:
                self._started = True
                self._thread.start()

    def _pump(self, ds: Dataset):
        i = 0
        error: Optional[BaseException] = None
        try:
            for ref in ds.iter_block_refs():
                if not self.equal:
                    self.queues[i % self.n].put(ref)
                    i += 1
                    continue
                block = ray_tpu.get(ref)
                rows = BlockAccessor(block).num_rows()
                if rows == 0:
                    continue
                size = (rows + self.n - 1) // self.n
                acc = BlockAccessor(block)
                for c in _builtin_range(self.n):
                    start = min(c * size, rows)
                    end = min((c + 1) * size, rows)
                    if end > start:
                        # Rotate which consumer gets the (larger) head slice.
                        target = (c + i) % self.n
                        self.queues[target].put(ray_tpu.put(acc.slice(start, end)))
                i += 1
        except BaseException as e:  # noqa: BLE001 — must reach the consumers
            error = e
        finally:
            # Execution errors propagate to every consumer rather than
            # silently truncating their streams.
            for q in self.queues:
                q.put(error if error is not None else None)


_builtin_range = range


class DataIterator:
    """Per-consumer iterator from streaming_split (ref: data/iterator.py:59)."""

    def __init__(self, coordinator: _SplitCoordinator, index: int):
        self._coord = coordinator
        self._index = index

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy") -> Iterator[Any]:
        from ray_tpu.data.block import rebatch

        self._coord.ensure_started()
        q = self._coord.queues[self._index]

        def block_stream():
            while True:
                ref = q.get()
                if ref is None:
                    return
                if isinstance(ref, BaseException):
                    raise ref
                yield ray_tpu.get(ref)

        yield from rebatch(block_stream(), batch_size, batch_format)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for batch in self.iter_batches(batch_size=None):
            n = len(next(iter(batch.values()))) if batch else 0
            for i in range(n):
                yield {k: v[i] for k, v in batch.items()}
