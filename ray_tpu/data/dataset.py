"""Dataset — lazy, streaming, distributed data (ref: python/ray/data/dataset.py:147).

Transforms append logical ops (plan.py); execution is streaming (executor.py)
and only happens on iteration/consumption, like the reference.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data import executor as ex
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks
from ray_tpu.data.plan import (
    ActorPoolStrategy,
    Aggregate,
    ComputeStrategy,
    Filter,
    FlatMap,
    InputData,
    Limit,
    LogicalOp,
    MapBatches,
    MapRows,
    RandomShuffle,
    Read,
    Zip,
    Repartition,
    Sort,
    Union as UnionOp,
)


class Dataset:
    def __init__(self, op: LogicalOp):
        self._op = op

    # ------------------------------------------------------------ transforms
    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy", compute: Optional[ComputeStrategy] = None,
                    num_tpus: Optional[float] = None,
                    concurrency: Union[int, Tuple[int, int], None] = None,
                    fn_constructor_args: tuple = (), **_compat) -> "Dataset":
        """(ref: dataset.py:397 map_batches — the batch-inference path).

        Stateful form: pass a class; it is constructed once per pool actor
        (TPU-pinned with num_tpus) and called per batch.  ``concurrency``
        takes an int (fixed pool) or a ``(min, max)`` tuple (the pool
        autoscales between the bounds while the op is backlogged).
        """
        fn_constructor = None
        the_fn = fn
        if isinstance(fn, type):
            if compute is not None and not isinstance(compute, ActorPoolStrategy):
                raise ValueError(
                    "map_batches with a callable class requires an actor pool "
                    "(stateful fn); pass compute=ActorPoolStrategy(...) or omit "
                    "compute (ref: dataset.py map_batches compute validation)")
            ctor_args = fn_constructor_args

            def fn_constructor():
                return fn(*ctor_args)

            def the_fn(batch, state):
                return state(batch)

            if compute is None:
                compute = _pool_strategy(concurrency, num_tpus)
        elif num_tpus or (isinstance(concurrency, tuple)
                          or (concurrency and concurrency > 1)):
            compute = compute or _pool_strategy(concurrency, num_tpus)
        return Dataset(MapBatches(self._op, the_fn, batch_size=batch_size,
                                  batch_format=batch_format, compute=compute,
                                  fn_constructor=fn_constructor))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return Dataset(MapRows(self._op, fn))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return Dataset(Filter(self._op, fn))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return Dataset(FlatMap(self._op, fn))

    def add_column(self, name: str, fn: Callable[[Dict], Any]) -> "Dataset":
        def add(row):
            row = dict(row)
            row[name] = fn(row)
            return row

        return Dataset(MapRows(self._op, add))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return Dataset(MapBatches(self._op, drop))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        return Dataset(MapBatches(self._op, select))

    def limit(self, n: int) -> "Dataset":
        return Dataset(Limit(self._op, n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(Repartition(self._op, num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(RandomShuffle(self._op, seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(Sort(self._op, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(UnionOp(self._op, [o._op for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned column concat (ref: dataset.py Dataset.zip).  Lazy:
        the right side materializes at execution time, the left streams
        through keeping its block boundaries.  Duplicate column names from
        `other` get a unique "_N" suffix, as in the reference."""
        return Dataset(Zip(self._op, other._op))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # ----------------------------------------------------------- consumption
    def iter_block_refs(self) -> Iterator[Any]:
        return ex.execute(self._op)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 0) -> Iterator[Any]:
        """(ref: iterator.py:94 iter_batches) — streaming, overlaps execution.

        ``prefetch_batches > 0`` pulls ahead on a background thread
        (data/ingest/prefetch.py) so block fetch + rebatch latency overlaps
        the consumer's work."""
        from ray_tpu.data.block import rebatch

        blocks = (ray_tpu.get(ref) for ref in self.iter_block_refs())
        batches = rebatch(blocks, batch_size, batch_format)
        if prefetch_batches > 0:
            from ray_tpu.data.ingest.prefetch import HostPrefetcher

            prefetcher = HostPrefetcher(batches, depth=prefetch_batches)
            try:
                yield from prefetcher
            finally:
                prefetcher.close()
            return
        yield from batches

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: str = "cpu") -> Iterator[Any]:
        """(ref: iterator.py iter_torch_batches) — dict of torch tensors."""
        import torch

        def to_tensor(k, v):
            if getattr(v, "dtype", None) is None or v.dtype.kind not in "biufc":
                return v  # non-numeric (strings/objects) stay numpy
            dt = dtypes.get(k) if isinstance(dtypes, dict) else dtypes
            if dt is None and v.dtype.kind == "u" and v.dtype.itemsize > 1:
                # torch has no uint16/32/64: upcast to a signed type.
                v = v.astype(np.int64)
            return torch.as_tensor(v, dtype=dt).to(device)

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            yield {k: to_tensor(k, v) for k, v in batch.items()}

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self.iter_block_refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.iter_rows(), n))

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(
            BlockAccessor(ray_tpu.get(r)).num_rows() for r in self.iter_block_refs())

    def schema(self):
        for ref in self.iter_block_refs():
            block = ray_tpu.get(ref)
            if block.num_rows > 0 or block.schema.names:
                return block.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def materialize(self) -> "Dataset":
        """(ref: dataset.py materialize) — execute now, pin blocks."""
        refs = list(self.iter_block_refs())
        return Dataset(InputData(refs))

    def to_pandas(self):
        import pandas as pd

        blocks = [ray_tpu.get(r) for r in self.iter_block_refs()]
        merged = concat_blocks(blocks)
        return merged.to_pandas()

    def min(self, col: str):
        return self._simple_agg("min", col)

    def max(self, col: str):
        return self._simple_agg("max", col)

    def sum(self, col: str):
        return self._simple_agg("sum", col)

    def mean(self, col: str):
        return self._simple_agg("mean", col)

    def std(self, col: str, ddof: int = 1):
        """Sample standard deviation (ref: dataset.py:2415 Dataset.std)."""
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(col, ddof=ddof))

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (ref: dataset.py:2154 unique) —
        computed distributed via a grouped count, keys collected."""
        ds = self.select_columns([column]).groupby(column).count()
        return sorted(r[column] for r in ds.take_all())

    def aggregate(self, *aggs) -> Any:
        """Global aggregation (ref: dataset.py:2198 aggregate(*AggregateFn)).

        One spec returns its scalar; several return a dict keyed by each
        spec's output name."""
        ds = Dataset(Aggregate(self._op, None, list(aggs)))
        row = ds.take_all()[0]
        if len(aggs) == 1:
            return next(iter(row.values()))
        return row

    def _simple_agg(self, fn: str, col: str):
        ds = Dataset(Aggregate(self._op, None, [(col, fn)]))
        rows = ds.take_all()
        return rows[0][f"{fn}({col})"]

    # --------------------------------------------------------------- splits
    def split(self, n: int) -> List["Dataset"]:
        """Materializing equal split (ref: dataset.py split)."""
        refs = list(self.iter_block_refs())
        blocks = [ray_tpu.get(r) for r in refs]
        merged = concat_blocks(blocks)
        acc = BlockAccessor(merged)
        total = acc.num_rows()
        size = (total + n - 1) // n if total else 0
        out = []
        for i in range(n):
            piece = acc.slice(min(i * size, total), min((i + 1) * size, total)) \
                if total else merged
            out.append(Dataset(InputData([ray_tpu.put(piece)])))
        return out

    def streaming_split(self, n: int, *, equal: bool = True) -> List["DataIterator"]:
        """Coordinated split for Train ingest (ref: StreamSplitDataIterator,
        _internal/iterator/stream_split_iterator.py:31): one shared execution,
        blocks dealt round-robin to n consumers."""
        coordinator = _SplitCoordinator(self, n, equal=equal)
        return [DataIterator(coordinator, i) for i in range(n)]

    # ---------------------------------------------------------------- writes
    def _write_blocks(self, path: str, ext: str, write_one) -> None:
        """Distributed write: each block is written BY A TASK, in parallel,
        without materializing on the driver (ref: logical write operators in
        _internal/logical/operators/write_operator.py)."""
        import os

        os.makedirs(path, exist_ok=True)
        write_task = ray_tpu.remote(write_one)
        refs = []
        for i, ref in enumerate(self.iter_block_refs()):
            out = os.path.join(path, f"part-{i:05d}.{ext}")
            refs.append(write_task.remote(ref, out))
        ray_tpu.get(refs)

    def write_parquet(self, path: str) -> None:
        self._write_blocks(path, "parquet", _write_block_parquet)

    def write_csv(self, path: str) -> None:
        self._write_blocks(path, "csv", _write_block_csv)

    def write_json(self, path: str) -> None:
        """Newline-delimited JSON, one file per block (ref: write_json)."""
        self._write_blocks(path, "json", _write_block_json)

    def write_tfrecords(self, path: str) -> None:
        """tf.train.Example TFRecord files, one per block — TensorFlow-
        readable framing + protos, no TF dependency (ref: write_tfrecords;
        data/tfrecords.py)."""
        self._write_blocks(path, "tfrecords", _write_block_tfrecords)

    def stats(self) -> str:
        return f"Dataset(plan={'->'.join(op.name for op in self._op.chain())})"

    def __repr__(self) -> str:
        return self.stats()


class GroupedData:
    """(ref: data/grouped_data.py)"""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def _agg(self, fn: str, col: str) -> Dataset:
        return Dataset(Aggregate(self._ds._op, self._key, [(col, fn)]))

    def sum(self, col: str) -> Dataset:
        return self._agg("sum", col)

    def min(self, col: str) -> Dataset:
        return self._agg("min", col)

    def max(self, col: str) -> Dataset:
        return self._agg("max", col)

    def mean(self, col: str) -> Dataset:
        return self._agg("mean", col)

    def count(self) -> Dataset:
        # Global count (key=None) counts rows of any column.
        col = self._key if self._key is not None else "*"
        return self._agg("count", col)

    def std(self, col: str, ddof: int = 1) -> Dataset:
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(col, ddof=ddof))

    def aggregate(self, *aggs) -> Dataset:
        """Multiple aggregations in one pass
        (ref: grouped_data.py:48 aggregate(*AggregateFn))."""
        return Dataset(Aggregate(self._ds._op, self._key, list(aggs)))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy") -> Dataset:
        """Apply ``fn`` to each group's batch; results concatenate into a new
        dataset (ref: grouped_data.py:93 map_groups)."""
        from ray_tpu.data.plan import MapGroups

        return Dataset(MapGroups(self._ds._op, self._key, fn,
                                 batch_format=batch_format))


class _SplitCoordinator:
    """Shared execution behind n DataIterators (ref: StreamSplitDataIterator's
    coordinator actor, _internal/iterator/stream_split_iterator.py:31).

    One pump thread *per epoch*: each round of ``iter_batches`` calls across
    the n consumers re-executes the plan, so multi-epoch training loops work
    (the reference's DataIterator re-executes per epoch too).  Queues hold
    object *refs* (data lives in the object store) and are unbounded so a
    consumer that drains late — or not at all — can never wedge the pump and
    starve its peers.

    equal=True deals row-slices so every consumer gets ~1/n of each block —
    a one-block dataset still feeds all n trainers (the reference guarantees
    balanced output for Train ingest).
    """

    def __init__(self, ds: Dataset, n: int, equal: bool = True):
        self.ds = ds
        self.n = n
        self.equal = equal
        self._lock = threading.Lock()
        self._epochs: Dict[int, dict] = {}

    def queue_for(self, index: int, epoch: int) -> "queue.SimpleQueue":
        with self._lock:
            state = self._epochs.get(epoch)
            if state is None:
                queues = [queue.SimpleQueue() for _ in _builtin_range(self.n)]
                state = {"queues": queues, "done": 0}
                self._epochs[epoch] = state
                threading.Thread(target=self._pump, args=(queues,), daemon=True,
                                 name=f"split-pump-e{epoch}").start()
            return state["queues"][index]

    def finished(self, index: int, epoch: int) -> None:
        with self._lock:
            state = self._epochs.get(epoch)
            if state is not None:
                state["done"] += 1
                if state["done"] >= self.n:
                    del self._epochs[epoch]

    def _pump(self, queues: List["queue.SimpleQueue"]):
        i = 0
        error: Optional[BaseException] = None
        try:
            for ref in self.ds.iter_block_refs():
                if not self.equal:
                    queues[i % self.n].put(ref)
                    i += 1
                    continue
                block = ray_tpu.get(ref)
                rows = BlockAccessor(block).num_rows()
                if rows == 0:
                    continue
                size = (rows + self.n - 1) // self.n
                acc = BlockAccessor(block)
                for c in _builtin_range(self.n):
                    start = min(c * size, rows)
                    end = min((c + 1) * size, rows)
                    if end > start:
                        # Rotate which consumer gets the (larger) head slice.
                        target = (c + i) % self.n
                        queues[target].put(ray_tpu.put(acc.slice(start, end)))
                i += 1
        except BaseException as e:  # noqa: BLE001 — must reach the consumers
            error = e
        finally:
            # Execution errors propagate to every consumer rather than
            # silently truncating their streams.
            for q in queues:
                q.put(error if error is not None else None)


_builtin_range = range


class DataIterator:
    """Per-consumer iterator from streaming_split (ref: data/iterator.py:59).

    Re-iterable: each ``iter_batches`` call consumes one fresh epoch of the
    shared execution.
    """

    def __init__(self, coordinator: _SplitCoordinator, index: int):
        self._coord = coordinator
        self._index = index
        self._epoch = 0

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 0) -> Iterator[Any]:
        from ray_tpu.data.block import rebatch

        epoch = self._epoch
        self._epoch += 1
        q = self._coord.queue_for(self._index, epoch)

        def block_stream():
            try:
                while True:
                    ref = q.get()
                    if ref is None:
                        return
                    if isinstance(ref, BaseException):
                        raise ref
                    yield ray_tpu.get(ref)
            finally:
                self._coord.finished(self._index, epoch)

        batches = rebatch(block_stream(), batch_size, batch_format)
        if prefetch_batches > 0:
            from ray_tpu.data.ingest.prefetch import HostPrefetcher

            prefetcher = HostPrefetcher(batches, depth=prefetch_batches)
            try:
                yield from prefetcher
            finally:
                prefetcher.close()
            return
        yield from batches

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for batch in self.iter_batches(batch_size=None):
            n = len(next(iter(batch.values()))) if batch else 0
            for i in range(n):
                yield {k: v[i] for k, v in batch.items()}


def _pool_strategy(concurrency, num_tpus):
    """concurrency int -> fixed pool; (min, max) tuple -> autoscaling pool
    (ref: dataset.py map_batches concurrency semantics)."""
    res = {"TPU": num_tpus} if num_tpus else {}
    if isinstance(concurrency, tuple):
        lo, hi = concurrency
        return ActorPoolStrategy(min_size=lo, max_size=hi, resources=res)
    return ActorPoolStrategy(size=concurrency or 1, resources=res)


def _write_block_parquet(block, out_path):
    import pyarrow.parquet as pq

    if block.num_rows:
        pq.write_table(block, out_path)


def _write_block_tfrecords(block, out_path):
    from ray_tpu.data.block import BlockAccessor
    from ray_tpu.data.tfrecords import row_to_example, write_records

    if block.num_rows:
        write_records(out_path, (row_to_example(row) for row in
                                 BlockAccessor(block).iter_rows()))


def _write_block_csv(block, out_path):
    import pyarrow.csv as pacsv

    if block.num_rows:
        pacsv.write_csv(block, out_path)


def _write_block_json(block, out_path):
    import json as _json

    if block.num_rows:
        with open(out_path, "w") as f:
            for row in block.to_pylist():
                f.write(_json.dumps(row, default=str) + "\n")
