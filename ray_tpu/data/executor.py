"""Streaming executor: runs the logical op chain over blocks with bounded
in-flight tasks.

(ref: python/ray/data/_internal/execution/streaming_executor.py:48 and
streaming_executor_state.py — an operator-DAG scheduling loop under resource
budgets with backpressure; task-pool and actor-pool map operators in
execution/operators/).  Structure kept: per-op transforms become tasks (or
actor calls for stateful compute) on the core runtime; blocks stream through
with a bounded number outstanding (backpressure), and outputs are yielded as
they finish — iteration overlaps with execution.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block, BlockAccessor, block_from_batch, block_from_rows, concat_blocks
from ray_tpu.data.plan import (
    AbstractMap,
    Aggregate,
    Filter,
    FlatMap,
    InputData,
    Limit,
    LogicalOp,
    MapBatches,
    MapRows,
    RandomShuffle,
    Read,
    Zip,
    Repartition,
    Sort,
    Union,
    fuse_maps,
)

#: Max map tasks in flight per operator (backpressure; ref:
#: backpressure_policy/concurrency_cap_backpressure_policy.py).
MAX_IN_FLIGHT = 8


def make_block_transform(op: AbstractMap) -> Callable[[Block], Block]:
    """Build the pure block->block function for a map-family logical op."""
    if getattr(op, "_pre_transformed", False):
        return op.fn
    if isinstance(op, MapBatches):
        batch_size = op.batch_size
        batch_format = op.batch_format
        fn = op.fn

        def map_batches(block: Block) -> Block:
            acc = BlockAccessor(block)
            n = acc.num_rows()
            if n == 0:
                return block
            size = batch_size or n
            outs = []
            for start in range(0, n, size):
                piece = BlockAccessor(acc.slice(start, min(start + size, n)))
                out = fn(piece.to_batch(batch_format))
                outs.append(block_from_batch(out))
            return concat_blocks(outs)

        return map_batches
    if isinstance(op, Filter):
        fn = op.fn

        def filter_block(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = [i for i, row in enumerate(acc.iter_rows()) if fn(row)]
            return acc.take(keep) if len(keep) < acc.num_rows() else block

        return filter_block
    if isinstance(op, FlatMap):
        fn = op.fn

        def flat_map(block: Block) -> Block:
            rows = []
            for row in BlockAccessor(block).iter_rows():
                rows.extend(fn(row))
            return block_from_rows(rows)

        return flat_map
    if isinstance(op, MapRows):
        fn = op.fn

        def map_rows(block: Block) -> Block:
            return block_from_rows([fn(row) for row in BlockAccessor(block).iter_rows()])

        return map_rows
    if isinstance(op, AbstractMap):
        return op.fn
    raise TypeError(f"not a map op: {op}")


class _ActorPool:
    """Stateful map execution on a pool of actors (ref:
    actor_pool_map_operator.py — the TPU batch-inference path: actors hold
    the model; blocks round-robin across them)."""

    def __init__(self, op: AbstractMap):
        transform = make_block_transform(op)
        fn_constructor = op.fn_constructor

        @ray_tpu.remote
        class MapWorker:
            def __init__(self):
                self.state = fn_constructor() if fn_constructor is not None else None

            def apply(self, block, transform=transform):
                if self.state is not None:
                    return transform_with_state(block, self.state)
                return transform(block)

        def transform_with_state(block, state):
            # fn is (batch, state) when a constructor is given.
            acc = BlockAccessor(block)
            n = acc.num_rows()
            if n == 0:
                return block
            size = getattr(op, "batch_size", None) or n
            fmt = getattr(op, "batch_format", "numpy")
            outs = []
            for start in range(0, n, size):
                piece = BlockAccessor(acc.slice(start, min(start + size, n)))
                outs.append(block_from_batch(op.fn(piece.to_batch(fmt), state)))
            return concat_blocks(outs)

        res = dict(op.compute.resources)
        self.actors = [
            MapWorker.options(resources=res or None, num_cpus=None if res else 1).remote()
            for _ in range(op.compute.pool_size)
        ]
        self._rr = 0

    def submit(self, block_ref):
        actor = self.actors[self._rr % len(self.actors)]
        self._rr += 1
        return actor.apply.remote(block_ref)

    def shutdown(self):
        for a in self.actors:
            ray_tpu.kill(a)


def execute(op: LogicalOp) -> Iterator[Any]:
    """Yield block ObjectRefs for the plan rooted at `op`, streaming."""
    ops = fuse_maps(op.chain())
    stream: Iterator[Any] = _source_stream(ops[0])
    for logical in ops[1:]:
        stream = _apply_op(stream, logical)
    return stream


def _source_stream(src: LogicalOp) -> Iterator[Any]:
    if isinstance(src, InputData):
        for b in src.blocks:
            yield b if isinstance(b, ray_tpu.ObjectRef) else ray_tpu.put(b)
        return
    if isinstance(src, Read):
        @ray_tpu.remote
        def do_read(task):
            return task()

        pending: List[Any] = []
        tasks = list(src.read_tasks)
        i = 0
        while i < len(tasks) or pending:
            while i < len(tasks) and len(pending) < MAX_IN_FLIGHT:
                pending.append(do_read.remote(tasks[i]))
                i += 1
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=10.0)
            for r in ready:
                yield r
        return
    raise TypeError(f"Unknown source op: {src}")


def _apply_op(stream: Iterator[Any], op: LogicalOp) -> Iterator[Any]:
    if isinstance(op, AbstractMap):
        if op.compute.kind == "actors":
            return _map_stream_actors(stream, op)
        return _map_stream_tasks(stream, op)
    if isinstance(op, Limit):
        return _limit_stream(stream, op.limit)
    if isinstance(op, (Repartition, RandomShuffle, Sort, Aggregate)):
        return _all_to_all(stream, op)
    if isinstance(op, Union):
        def union_stream():
            yield from stream
            for other in op.others:
                yield from execute(other)

        return union_stream()
    if isinstance(op, Zip):
        return _zip_stream(stream, op)
    raise TypeError(f"Unknown op: {op}")


def _unique_column_name(name: str, taken) -> str:
    if name not in taken:
        return name
    i = 1
    while f"{name}_{i}" in taken:
        i += 1
    return f"{name}_{i}"


def _zip_stream(stream: Iterator[Any], op: "Zip") -> Iterator[Any]:
    """Materialize the right side, slice it along the left's block
    boundaries (runs at consumption time — the plan stays lazy)."""
    import pyarrow as pa

    right_blocks = [ray_tpu.get(r) for r in execute(op.other)]
    right = pa.concat_tables(right_blocks) if right_blocks else pa.table({})
    offset = 0
    for ref in stream:
        left = ray_tpu.get(ref)
        n = left.num_rows
        if offset + n > right.num_rows:
            raise ValueError(
                f"zip requires equal row counts; right side has only "
                f"{right.num_rows} rows")
        rslice = right.slice(offset, n)
        offset += n
        taken = set(left.column_names)
        combined = left
        for name in rslice.column_names:
            out = _unique_column_name(name, taken)
            taken.add(out)
            combined = combined.append_column(out, rslice[name])
        yield ray_tpu.put(combined)
    if offset != right.num_rows:
        raise ValueError(
            f"zip requires equal row counts: left has {offset}, right has "
            f"{right.num_rows}")


def _map_stream_tasks(stream: Iterator[Any], op: AbstractMap) -> Iterator[Any]:
    transform = make_block_transform(op)

    @ray_tpu.remote
    def apply(block):
        return transform(block)

    pending: List[Any] = []
    done = False
    while not done or pending:
        while not done and len(pending) < MAX_IN_FLIGHT:
            try:
                block_ref = next(stream)
            except StopIteration:
                done = True
                break
            pending.append(apply.remote(block_ref))
        if pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=30.0)
            for r in ready:
                yield r


def _map_stream_actors(stream: Iterator[Any], op: AbstractMap) -> Iterator[Any]:
    pool = _ActorPool(op)
    try:
        pending: List[Any] = []
        done = False
        while not done or pending:
            while not done and len(pending) < max(MAX_IN_FLIGHT, op.compute.pool_size):
                try:
                    block_ref = next(stream)
                except StopIteration:
                    done = True
                    break
                pending.append(pool.submit(block_ref))
            if pending:
                ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=60.0)
                for r in ready:
                    yield r
    finally:
        pool.shutdown()


def _limit_stream(stream: Iterator[Any], limit: int) -> Iterator[Any]:
    seen = 0
    for ref in stream:
        if seen >= limit:
            return
        block = ray_tpu.get(ref)
        n = BlockAccessor(block).num_rows()
        if seen + n <= limit:
            seen += n
            yield ref
        else:
            yield ray_tpu.put(BlockAccessor(block).slice(0, limit - seen))
            seen = limit
            return


def _all_to_all(stream: Iterator[Any], op: LogicalOp) -> Iterator[Any]:
    """Materializing ops (ref: planner/exchange/ shuffle)."""
    blocks = [ray_tpu.get(r) for r in stream]
    combined = concat_blocks(blocks)
    acc = BlockAccessor(combined)
    n = acc.num_rows()

    if isinstance(op, Sort):
        import pyarrow.compute as pc

        idx = pc.sort_indices(
            combined,
            sort_keys=[(op.key, "descending" if op.descending else "ascending")])
        combined = combined.take(idx)
        yield ray_tpu.put(combined)
        return
    if isinstance(op, RandomShuffle):
        rng = np.random.default_rng(op.seed)
        perm = rng.permutation(n)
        yield ray_tpu.put(acc.take(list(map(int, perm))))
        return
    if isinstance(op, Repartition):
        k = max(1, op.num_blocks)
        size = max(1, (n + k - 1) // k)
        for start in range(0, n, size):
            yield ray_tpu.put(acc.slice(start, min(start + size, n)))
        return
    if isinstance(op, Aggregate):
        yield ray_tpu.put(_aggregate(combined, op))
        return
    raise TypeError(op)


def _aggregate(block: Block, op: Aggregate) -> Block:
    import pyarrow as pa

    acc = BlockAccessor(block)
    if op.key is None:
        row: Dict[str, Any] = {}
        for col, fn in op.aggs:
            if col == "*":  # global row count
                row[f"{fn}({col})"] = acc.num_rows()
                continue
            vals = block_mod.column_to_numpy(block, col)
            row[f"{fn}({col})"] = _agg_fn(fn)(vals)
        return block_from_rows([row])
    tbl = block.group_by(op.key).aggregate([(c, _arrow_agg(f)) for c, f in op.aggs])
    return tbl


def _agg_fn(name: str):
    return {"sum": np.sum, "min": np.min, "max": np.max, "mean": np.mean,
            "count": len, "std": np.std}[name]


def _arrow_agg(name: str) -> str:
    return {"sum": "sum", "min": "min", "max": "max", "mean": "mean",
            "count": "count", "std": "stddev"}[name]
