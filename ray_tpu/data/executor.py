"""Streaming executor: runs the logical op chain over blocks with bounded
in-flight tasks.

(ref: python/ray/data/_internal/execution/streaming_executor.py:48 and
streaming_executor_state.py — an operator-DAG scheduling loop under resource
budgets with backpressure; task-pool and actor-pool map operators in
execution/operators/).  Structure kept: per-op transforms become tasks (or
actor calls for stateful compute) on the core runtime; blocks stream through
with a bounded number outstanding (backpressure), and outputs are yielded as
they finish — iteration overlaps with execution.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block, BlockAccessor, block_from_batch, block_from_rows, concat_blocks
from ray_tpu.data.plan import (
    AbstractMap,
    Aggregate,
    Filter,
    FlatMap,
    InputData,
    Limit,
    LogicalOp,
    MapBatches,
    MapGroups,
    MapRows,
    RandomShuffle,
    Read,
    Zip,
    Repartition,
    Sort,
    Union,
    fuse_maps,
)

#: Max map tasks in flight per operator (backpressure; ref:
#: backpressure_policy/concurrency_cap_backpressure_policy.py).
MAX_IN_FLIGHT = 8


class ResourceBudget:
    """Per-op in-flight budget: a task cap AND a bytes cap
    (ref: execution/resource_manager.py + backpressure_policy/ — operators
    may not hold more than their share of object-store memory in flight).

    Block sizes are learned from completed blocks (EMA), so the byte cap
    tightens as soon as real sizes are observed; until then the task cap
    alone applies.  The whole pipeline is pull-based, so a slow consumer
    stops new launches at the next cap check — memory stays bounded at
    cap * avg_block regardless of consumer speed."""

    def __init__(self, task_cap: int = MAX_IN_FLIGHT,
                 mem_fraction: float = 0.25,
                 mem_budget: Optional[int] = None):
        self._task_cap = max(1, task_cap)
        if mem_budget is not None:
            # Explicit byte budget (streaming ingest passes its window
            # budget) — skip the store-capacity heuristic entirely.
            self._mem_budget = max(1 << 20, int(mem_budget))
            self._avg_block = 0.0
            return
        store_cap = 0
        try:
            from ray_tpu._private.runtime import runtime_or_none

            runtime = runtime_or_none()
            if runtime is not None:
                store_cap = runtime.store.capacity_bytes
        except Exception:
            pass
        if not store_cap:
            from ray_tpu._private.config import GLOBAL_CONFIG

            store_cap = GLOBAL_CONFIG.object_store_memory or (1 << 30)
        self._mem_budget = max(64 << 20, int(store_cap * mem_fraction))
        self._avg_block: float = 0.0

    def observe_ref(self, ref) -> None:
        """Learn block size from the store's recorded entry size — no
        driver-side get: fetching every block just to measure it would
        defeat the pass-by-reference stream (and restore spilled blocks)."""
        try:
            from ray_tpu._private.runtime import runtime_or_none

            runtime = runtime_or_none()
            nbytes = runtime.store.size_of(ref.id) if runtime else 0
        except Exception:
            return
        if nbytes:
            self._observe_bytes(nbytes)

    def observe_block(self, block) -> None:
        try:
            nbytes = BlockAccessor(block).size_bytes()
        except Exception:
            return
        self._observe_bytes(nbytes)

    def _observe_bytes(self, nbytes: float) -> None:
        self._avg_block = (0.7 * self._avg_block + 0.3 * nbytes
                           if self._avg_block else float(nbytes))

    def cap(self) -> int:
        if self._avg_block > 0:
            by_mem = int(self._mem_budget // self._avg_block)
            return max(1, min(self._task_cap, by_mem))
        return self._task_cap


def make_block_transform(op: AbstractMap) -> Callable[[Block], Block]:
    """Build the pure block->block function for a map-family logical op."""
    if getattr(op, "_pre_transformed", False):
        return op.fn
    if isinstance(op, MapBatches):
        batch_size = op.batch_size
        batch_format = op.batch_format
        fn = op.fn

        def map_batches(block: Block) -> Block:
            acc = BlockAccessor(block)
            n = acc.num_rows()
            if n == 0:
                return block
            size = batch_size or n
            outs = []
            for start in range(0, n, size):
                piece = BlockAccessor(acc.slice(start, min(start + size, n)))
                out = fn(piece.to_batch(batch_format))
                outs.append(block_from_batch(out))
            return concat_blocks(outs)

        return map_batches
    if isinstance(op, Filter):
        fn = op.fn

        def filter_block(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = [i for i, row in enumerate(acc.iter_rows()) if fn(row)]
            return acc.take(keep) if len(keep) < acc.num_rows() else block

        return filter_block
    if isinstance(op, FlatMap):
        fn = op.fn

        def flat_map(block: Block) -> Block:
            rows = []
            for row in BlockAccessor(block).iter_rows():
                rows.extend(fn(row))
            return block_from_rows(rows)

        return flat_map
    if isinstance(op, MapRows):
        fn = op.fn

        def map_rows(block: Block) -> Block:
            return block_from_rows([fn(row) for row in BlockAccessor(block).iter_rows()])

        return map_rows
    if isinstance(op, AbstractMap):
        return op.fn
    raise TypeError(f"not a map op: {op}")


class _ActorPool:
    """Stateful map execution on a pool of actors (ref:
    actor_pool_map_operator.py — the TPU batch-inference path: actors hold
    the model; blocks round-robin across them)."""

    def __init__(self, op: AbstractMap):
        transform = make_block_transform(op)
        fn_constructor = op.fn_constructor

        @ray_tpu.remote
        class MapWorker:
            def __init__(self):
                self.state = fn_constructor() if fn_constructor is not None else None

            def apply(self, block, transform=transform):
                if self.state is not None:
                    return transform_with_state(block, self.state)
                return transform(block)

        def transform_with_state(block, state):
            # fn is (batch, state) when a constructor is given.
            acc = BlockAccessor(block)
            n = acc.num_rows()
            if n == 0:
                return block
            size = getattr(op, "batch_size", None) or n
            fmt = getattr(op, "batch_format", "numpy")
            outs = []
            for start in range(0, n, size):
                piece = BlockAccessor(acc.slice(start, min(start + size, n)))
                outs.append(block_from_batch(op.fn(piece.to_batch(fmt), state)))
            return concat_blocks(outs)

        res = dict(op.compute.resources)
        self._actor_req = dict(res) if res else {"CPU": 1.0}
        self._mk_actor = lambda: MapWorker.options(
            resources=res or None, num_cpus=None if res else 1).remote()
        self.max_size = max(op.compute.max_size, op.compute.pool_size)
        self.actors = [self._mk_actor() for _ in range(op.compute.pool_size)]
        self._rr = 0

    def size(self) -> int:
        return len(self.actors)

    def maybe_scale_up(self) -> bool:
        if len(self.actors) >= self.max_size:
            return False
        # One scale-up in flight at a time: actor leases are acquired
        # asynchronously, so available_resources() does not yet reflect an
        # actor we just appended — stacking scale-ups on that stale reading
        # could take the last CPU anyway.
        from ray_tpu._private.runtime import get_runtime

        runtime = get_runtime()
        for a in self.actors:
            state = runtime.get_actor_state(a._ray_actor_id)
            if state is not None and state.state == "PENDING_CREATION":
                return False
        # Never scale into the last CPU: actors hold their lease for life,
        # and a pool that absorbs every slot starves the upstream read/map
        # TASKS forever — deadlock by oversubscription (ref:
        # resource_manager.py reserves budgets per operator).
        avail = ray_tpu.available_resources()
        for key, need in self._actor_req.items():
            headroom = 1.0 if key == "CPU" else 0.0
            if avail.get(key, 0.0) < need + headroom:
                return False
        self.actors.append(self._mk_actor())
        return True

    def submit(self, block_ref):
        actor = self.actors[self._rr % len(self.actors)]
        self._rr += 1
        return actor.apply.remote(block_ref)

    def shutdown(self):
        for a in self.actors:
            ray_tpu.kill(a)


def execute(op: LogicalOp) -> Iterator[Any]:
    """Yield block ObjectRefs for the plan rooted at `op`, streaming."""
    ops = fuse_maps(op.chain())
    stream: Iterator[Any] = _source_stream(ops[0])
    for logical in ops[1:]:
        stream = _apply_op(stream, logical)
    return stream


def _source_stream(src: LogicalOp) -> Iterator[Any]:
    if isinstance(src, InputData):
        for b in src.blocks:
            yield b if isinstance(b, ray_tpu.ObjectRef) else ray_tpu.put(b)
        return
    if isinstance(src, Read):
        @ray_tpu.remote
        def do_read(task):
            return task()

        pending: List[Any] = []
        tasks = list(src.read_tasks)
        i = 0
        while i < len(tasks) or pending:
            while i < len(tasks) and len(pending) < MAX_IN_FLIGHT:
                pending.append(do_read.remote(tasks[i]))
                i += 1
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=10.0)
            for r in ready:
                yield r
        return
    raise TypeError(f"Unknown source op: {src}")


def _apply_op(stream: Iterator[Any], op: LogicalOp) -> Iterator[Any]:
    if isinstance(op, AbstractMap):
        if op.compute.kind == "actors":
            return _map_stream_actors(stream, op)
        return _map_stream_tasks(stream, op)
    if isinstance(op, Limit):
        return _limit_stream(stream, op.limit)
    if isinstance(op, (Repartition, RandomShuffle, Sort, Aggregate, MapGroups)):
        return _all_to_all(stream, op)
    if isinstance(op, Union):
        def union_stream():
            yield from stream
            for other in op.others:
                yield from execute(other)

        return union_stream()
    if isinstance(op, Zip):
        return _zip_stream(stream, op)
    raise TypeError(f"Unknown op: {op}")


def _unique_column_name(name: str, taken) -> str:
    if name not in taken:
        return name
    i = 1
    while f"{name}_{i}" in taken:
        i += 1
    return f"{name}_{i}"


def _zip_stream(stream: Iterator[Any], op: "Zip") -> Iterator[Any]:
    """Materialize the right side, slice it along the left's block
    boundaries (runs at consumption time — the plan stays lazy)."""
    import pyarrow as pa

    right_blocks = [ray_tpu.get(r) for r in execute(op.other)]
    right = pa.concat_tables(right_blocks) if right_blocks else pa.table({})
    offset = 0
    for ref in stream:
        left = ray_tpu.get(ref)
        n = left.num_rows
        if offset + n > right.num_rows:
            raise ValueError(
                f"zip requires equal row counts; right side has only "
                f"{right.num_rows} rows")
        rslice = right.slice(offset, n)
        offset += n
        taken = set(left.column_names)
        combined = left
        for name in rslice.column_names:
            out = _unique_column_name(name, taken)
            taken.add(out)
            combined = combined.append_column(out, rslice[name])
        yield ray_tpu.put(combined)
    if offset != right.num_rows:
        raise ValueError(
            f"zip requires equal row counts: left has {offset}, right has "
            f"{right.num_rows}")


def _map_stream_tasks(stream: Iterator[Any], op: AbstractMap) -> Iterator[Any]:
    transform = make_block_transform(op)

    @ray_tpu.remote
    def apply(block):
        return transform(block)

    budget = ResourceBudget()
    pending: List[Any] = []
    done = False
    while not done or pending:
        while not done and len(pending) < budget.cap():
            try:
                block_ref = next(stream)
            except StopIteration:
                done = True
                break
            pending.append(apply.remote(block_ref))
        if pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=30.0)
            for r in ready:
                budget.observe_ref(r)
                yield r


def _map_stream_actors(stream: Iterator[Any], op: AbstractMap) -> Iterator[Any]:
    pool = _ActorPool(op)
    budget = ResourceBudget(task_cap=max(MAX_IN_FLIGHT, op.compute.max_size))
    try:
        pending: List[Any] = []
        done = False
        while not done or pending:
            cap = min(budget.cap(), 2 * pool.size())
            while not done and len(pending) < cap:
                try:
                    block_ref = next(stream)
                except StopIteration:
                    done = True
                    break
                pending.append(pool.submit(block_ref))
            if not done and pending and len(pending) >= cap:
                # Backlogged at current capacity: autoscale up to max_size
                # (ref: actor-pool autoscaling in data/_internal/execution/
                # autoscaler/).
                pool.maybe_scale_up()
            if pending:
                ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=60.0)
                for r in ready:
                    budget.observe_ref(r)
                    yield r
    finally:
        pool.shutdown()


def _limit_stream(stream: Iterator[Any], limit: int) -> Iterator[Any]:
    seen = 0
    for ref in stream:
        if seen >= limit:
            return
        block = ray_tpu.get(ref)
        n = BlockAccessor(block).num_rows()
        if seen + n <= limit:
            seen += n
            yield ref
        else:
            yield ray_tpu.put(BlockAccessor(block).slice(0, limit - seen))
            seen = limit
            return


def _all_to_all(stream: Iterator[Any], op: LogicalOp) -> Iterator[Any]:
    """Exchange ops as DISTRIBUTED TASK STAGES (ref: planner/exchange/
    push_based_shuffle_task_scheduler.py): map tasks partition each block
    (hash/range/random), reduce tasks merge per partition.  The driver
    touches only refs and sample/count metadata — never the block data
    (the r2 driver-side concat_blocks of the whole dataset is gone)."""
    from ray_tpu.data import exchange

    refs = list(stream)
    if not refs:
        return
    if isinstance(op, Sort):
        yield from exchange.sorted_exchange(refs, op.key, op.descending)
        return
    if isinstance(op, RandomShuffle):
        yield from exchange.shuffle_exchange(refs, op.seed)
        return
    if isinstance(op, Repartition):
        yield from exchange.repartition_exchange(refs, op.num_blocks)
        return
    if isinstance(op, Aggregate):
        if op.key is None:
            yield ray_tpu.put(exchange.global_aggregate(refs, op))
        else:
            yield from exchange.hash_exchange(refs, op, "aggregate")
        return
    if isinstance(op, MapGroups):
        if op.key is None:
            # Keyless map_groups = ONE group by definition, so the UDF must
            # see the whole dataset in one task — exactly the reference's
            # behavior (grouped_data.py:188 repartition(1) when key is
            # None; its docstring warns each group must fit one node).
            # Per-group distribution applies only with a key (hash path).
            yield exchange._reduce_map_groups.remote(op, *refs)
        else:
            yield from exchange.hash_exchange(refs, op, "map_groups")
        return
    raise TypeError(op)


def _normalize_agg(agg) -> tuple:
    """(col, fn, spec) from a legacy tuple or an AggregateFn instance."""
    from ray_tpu.data.aggregate import AggregateFn

    if isinstance(agg, AggregateFn):
        return agg.on if agg.on is not None else "*", agg.fn_name, agg
    col, fn = agg
    return col, fn, None


def _aggregate(block: Block, op: Aggregate) -> Block:
    acc = BlockAccessor(block)
    if op.key is None:
        row: Dict[str, Any] = {}
        for agg in op.aggs:
            col, fn, spec = _normalize_agg(agg)
            name = spec.output_name if spec is not None else f"{fn}({col})"
            if col == "*" or fn == "count":  # row/value count
                row[name] = acc.num_rows() if col == "*" \
                    else len(block_mod.column_to_numpy(block, col))
                continue
            vals = block_mod.column_to_numpy(block, col)
            row[name] = _agg_fn(fn, spec)(vals)
        return block_from_rows([row])
    if any(_normalize_agg(a)[1] in ("quantile", "unique") for a in op.aggs):
        # Arrow's group_by has no exact kernel for these: sort by key and
        # reduce each group slice with numpy (ref: the reference's
        # sort-based per-group path — push_based_shuffle + SortAggregate).
        # Exactness holds because the hash exchange lands ALL rows of a key
        # in one partition before this runs.
        return _aggregate_sorted(block, op)
    arrow_aggs = []
    renames: Dict[str, str] = {}
    for agg in op.aggs:
        col, fn, spec = _normalize_agg(agg)
        if col == "*":
            col = op.key
            fn = "count"
        arrow_spec = _arrow_agg(col, fn, spec)
        arrow_aggs.append(arrow_spec)
        if spec is not None and spec.alias_name:
            # Arrow names outputs "<col>_<kernel>"; honor the spec's alias.
            renames[f"{col}_{arrow_spec[1]}"] = spec.alias_name
    tbl = block.group_by(op.key).aggregate(arrow_aggs)
    if renames:
        tbl = tbl.rename_columns(
            [renames.get(c, c) for c in tbl.column_names])
    return tbl


def _aggregate_sorted(block: Block, op: Aggregate) -> Block:
    """Per-group aggregation by sort + boundary slicing: supports every
    agg fn including the order-statistics ones arrow's group_by cannot
    (quantile, unique)."""
    tbl = block.sort_by(op.key)
    keys = block_mod.column_to_numpy(tbl, op.key)
    n = len(keys)
    if n == 0:
        return block_from_rows([])
    changed = keys[1:] != keys[:-1]
    if np.issubdtype(np.asarray(keys).dtype, np.floating):
        # NaN != NaN would split the null group into one row per NaN;
        # adjacent NaNs (sorted together) are ONE group, like arrow's.
        both_nan = np.isnan(keys[1:]) & np.isnan(keys[:-1])
        changed = changed & ~both_nan
    boundaries = [0] + [i + 1 for i in np.nonzero(changed)[0]] + [n]
    cols: Dict[str, np.ndarray] = {}
    rows: List[Dict[str, Any]] = []
    for gi in range(len(boundaries) - 1):
        start, end = boundaries[gi], boundaries[gi + 1]
        row: Dict[str, Any] = {op.key: keys[start]}
        for agg in op.aggs:
            col, fn, spec = _normalize_agg(agg)
            if col == "*":
                col, fn = op.key, "count"
            if spec is not None and spec.alias_name:
                name = spec.alias_name
            else:
                # Match the arrow path's "<col>_<kernel>" naming.
                kernel = {"std": "stddev"}.get(fn, fn)
                name = f"{col}_{kernel}"
            if col not in cols:
                cols[col] = block_mod.column_to_numpy(tbl, col)
            vals = cols[col][start:end]
            if fn == "count":
                # Match arrow's count kernel: only VALID values (nulls in
                # float columns arrive here as NaN).
                v = np.asarray(vals)
                row[name] = (int(np.sum(~np.isnan(v)))
                             if np.issubdtype(v.dtype, np.floating)
                             else len(v))
            else:
                row[name] = _agg_fn(fn, spec)(vals)
        rows.append(row)
    return block_from_rows(rows)


def _agg_fn(name: str, spec=None):
    if name == "std":
        ddof = getattr(spec, "ddof", 1)
        return lambda v: np.std(v, ddof=ddof)
    if name == "quantile":
        q = getattr(spec, "q", 0.5)
        return lambda v: np.quantile(v, q)
    if name == "unique":
        return lambda v: sorted(set(np.asarray(v).tolist()))
    return {"sum": np.sum, "min": np.min, "max": np.max, "mean": np.mean,
            "count": len}[name]


def _arrow_agg(col: str, name: str, spec=None) -> tuple:
    """(column, arrow-kernel[, options]) for TableGroupBy.aggregate."""
    import pyarrow.compute as pc

    if name == "std":
        return (col, "stddev",
                pc.VarianceOptions(ddof=getattr(spec, "ddof", 1)))
    if name in ("quantile", "unique"):
        raise NotImplementedError(
            f"{name} is a global aggregation; arrow's group_by has no exact "
            f"kernel for it (ref: the reference sorts per group instead — "
            f"use map_groups for per-group custom reductions)")
    kernel = {"sum": "sum", "min": "min", "max": "max", "mean": "mean",
              "count": "count"}[name]
    return (col, kernel)


def _map_groups(block: Block, op: MapGroups) -> Block:
    """Sort by key, slice group boundaries, apply the UDF per group batch,
    concat results (ref: grouped_data.py:93 map_groups)."""
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return block
    if op.key is None:
        groups = [block]
    else:
        keys = block_mod.column_to_numpy(block, op.key)
        order = np.argsort(keys, kind="stable")
        sorted_block = acc.take(list(map(int, order)))
        sorted_keys = keys[order]
        boundaries = [0] + [
            i for i in range(1, n) if sorted_keys[i] != sorted_keys[i - 1]
        ] + [n]
        sacc = BlockAccessor(sorted_block)
        groups = [sacc.slice(boundaries[i], boundaries[i + 1])
                  for i in range(len(boundaries) - 1)]
    out_blocks = []
    for g in groups:
        batch = BlockAccessor(g).to_batch(op.batch_format)
        result = op.fn(batch)
        out_blocks.append(block_from_batch(result))
    return concat_blocks(out_blocks)
