"""Trial schedulers (ref: python/ray/tune/schedulers/ — trial_scheduler.py
TrialScheduler, async_hyperband.py ASHAScheduler, hyperband.py,
median_stopping_rule.py, pbt.py PopulationBasedTraining).

The controller calls ``on_trial_result`` after every reported result and acts
on the returned decision.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional


class TrialScheduler:
    """(ref: tune/schedulers/trial_scheduler.py:23)"""

    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]) -> bool:
        return True

    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict[str, Any]]) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def choose_trial_to_run(self, pending: List) -> Optional[Any]:
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order
    (ref: trial_scheduler.py FIFOScheduler)."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (ref: tune/schedulers/async_hyperband.py
    AsyncHyperBandScheduler — rung-based promotion with reduction_factor).

    A trial reaching a rung milestone is stopped unless its metric is in the
    top 1/reduction_factor of results recorded at that rung.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration", max_t: int = 100,
                 grace_period: int = 1, reduction_factor: float = 4,
                 brackets: int = 1):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self._brackets: List[Dict[int, List[float]]] = []
        for b in range(brackets):
            rungs: Dict[int, List[float]] = {}
            t = grace_period * (reduction_factor ** b)
            while t < max_t:
                rungs[int(t)] = []
                t *= reduction_factor
            self._brackets.append(rungs)
        self._trial_bracket: Dict[str, int] = {}
        self._recorded: set = set()  # (trial_id, milestone) pairs already rung-recorded
        self._rng = random.Random(0)

    def set_search_properties(self, metric, mode) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_add(self, trial) -> None:
        self._trial_bracket[trial.trial_id] = self._rng.randrange(len(self._brackets))

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        score = result.get(self.metric)
        if t is None or score is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        rungs = self._brackets[self._trial_bracket.get(trial.trial_id, 0)]
        # Record once per rung at the first result past the milestone (ref:
        # async_hyperband.py _Bracket.on_result), but keep comparing the
        # trial's current score against the rung cutoff on later results too —
        # an early arrival judged against an empty rung must still be cuttable
        # once peers fill the rung in.
        for milestone in sorted(rungs, reverse=True):
            if t < milestone:
                continue
            key = (trial.trial_id, milestone)
            if key not in self._recorded:
                self._recorded.add(key)
                rungs[milestone].append(float(score))
            if not self._top_k(float(score), rungs[milestone]):
                return self.STOP
            break
        return self.CONTINUE

    def _top_k(self, score: float, recorded: List[float]) -> bool:
        if len(recorded) < self.rf:
            return True  # not enough data to cut yet
        ranked = sorted(recorded, reverse=(self.mode == "max"))
        cutoff = ranked[max(0, int(math.ceil(len(ranked) / self.rf)) - 1)]
        return (score >= cutoff) if self.mode == "max" else (score <= cutoff)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of the
    running means of other trials at the same step
    (ref: tune/schedulers/median_stopping_rule.py:18)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration", grace_period: int = 1,
                 min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._means: Dict[str, List[float]] = {}

    def set_search_properties(self, metric, mode) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        score = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if score is None:
            return self.CONTINUE
        hist = self._means.setdefault(trial.trial_id, [])
        hist.append(float(score))
        if t < self.grace_period or len(self._means) < self.min_samples:
            return self.CONTINUE
        my_mean = sum(hist) / len(hist)
        others = [sum(h) / len(h) for tid, h in self._means.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples - 1:
            return self.CONTINUE
        others.sort()
        median = others[len(others) // 2]
        worse = my_mean < median if self.mode == "max" else my_mean > median
        return self.STOP if worse else self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (ref: tune/schedulers/pbt.py:247 PopulationBasedTraining).

    Every ``perturbation_interval`` steps, a bottom-quantile trial exploits a
    top-quantile trial — clone its checkpoint + config — and explores by
    perturbing mutable hyperparameters.  The controller implements the clone
    by restarting the trial actor from the donor's checkpoint; the decision
    payload rides on ``trial.pbt_exploit``.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None,
                 perturbation_factors: tuple = (1.2, 0.8)):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.factors = perturbation_factors
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def on_trial_add(self, trial) -> None:
        self._trials[trial.trial_id] = trial

    def on_trial_complete(self, trial, result) -> None:
        self._trials.pop(trial.trial_id, None)
        self._scores.pop(trial.trial_id, None)

    def on_trial_error(self, trial) -> None:
        self.on_trial_complete(trial, None)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        score = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if score is None:
            return self.CONTINUE
        self._scores[trial.trial_id] = float(score)
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1],
                        reverse=(self.mode == "max"))
        n = len(ranked)
        if n < 2:
            return self.CONTINUE
        k = max(1, int(n * self.quantile))
        top = [tid for tid, _ in ranked[:k]]
        bottom = {tid for tid, _ in ranked[-k:]}
        if trial.trial_id in bottom and trial.trial_id not in top:
            donor_id = top[self._rng.randrange(len(top))]
            donor = self._trials.get(donor_id)
            if donor is not None and donor_id != trial.trial_id:
                trial.pbt_exploit = {
                    "donor": donor,
                    "new_config": self._explore(dict(donor.config)),
                }
                return self.PAUSE  # controller turns PAUSE+pbt_exploit into clone
        return self.CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search_space import Domain

        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if isinstance(spec, list):
                config[key] = self._rng.choice(spec)
            elif isinstance(spec, Domain):
                if self._rng.random() < 0.25:
                    config[key] = spec.sample(self._rng)
                elif isinstance(config[key], (int, float)):
                    factor = self._rng.choice(self.factors)
                    config[key] = type(config[key])(config[key] * factor)
            elif callable(spec):
                config[key] = spec()
        return config


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (ref: tune/schedulers/pb2.py PB2 — PBT where
    explore() picks new hyperparameters with a GP-bandit (UCB) fit on
    observed (hyperparams -> reward improvement) data instead of random
    perturbation; Parker-Holder et al. 2020).

    Requires numeric search bounds: ``hyperparam_mutations`` values must be
    ``[low, high]`` lists or tune domains with numeric bounds.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode, time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations=hyperparam_bounds,
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds: Dict[str, tuple] = {}
        for key, spec in (hyperparam_bounds or {}).items():
            if isinstance(spec, (list, tuple)) and len(spec) == 2:
                self.bounds[key] = (float(spec[0]), float(spec[1]))
            else:
                from ray_tpu.tune.search_space import Domain

                if isinstance(spec, Domain) and hasattr(spec, "lower"):
                    self.bounds[key] = (float(spec.lower), float(spec.upper))
                else:
                    raise ValueError(
                        f"PB2 needs numeric [low, high] bounds for {key!r}")
        #: GP training data: rows of (normalized hyperparams, reward delta)
        self._X: List[List[float]] = []
        self._y: List[float] = []
        self._prev_score: Dict[str, float] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        score = result.get(self.metric)
        if score is not None:
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                self._X.append(self._normalize(trial.config))
                delta = float(score) - prev
                self._y.append(delta if self.mode == "max" else -delta)
                if len(self._y) > 512:  # bound GP cost
                    self._X.pop(0)
                    self._y.pop(0)
            self._prev_score[trial.trial_id] = float(score)
        return super().on_trial_result(trial, result)

    def _normalize(self, config: Dict[str, Any]) -> List[float]:
        row = []
        for key, (lo, hi) in sorted(self.bounds.items()):
            v = float(config.get(key, lo))
            row.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return row

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """GP-UCB over candidate configs (the PB2 selection step)."""
        import numpy as np

        keys = sorted(self.bounds)
        if len(self._y) < 4:
            # Cold start: uniform sample inside bounds.
            for k in keys:
                lo, hi = self.bounds[k]
                config[k] = type(config.get(k, lo))(self._rng.uniform(lo, hi))
            return config
        X = np.asarray(self._X)
        y = np.asarray(self._y)
        y = (y - y.mean()) / (y.std() + 1e-8)

        def kernel(A, B, ls=0.2):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * ls * ls))

        K = kernel(X, X) + 1e-4 * np.eye(len(X))
        Kinv_y = np.linalg.solve(K, y)
        # Candidate pool: random points in the unit box.
        cands = np.asarray([[self._rng.random() for _ in keys]
                            for _ in range(64)])
        Ks = kernel(cands, X)
        mu = Ks @ Kinv_y
        Kinv_Ks = np.linalg.solve(K, Ks.T)
        var = np.clip(1.0 - np.einsum("ij,ji->i", Ks, Kinv_Ks), 1e-6, None)
        ucb = mu + 1.0 * np.sqrt(var)
        best = cands[int(np.argmax(ucb))]
        for k, u in zip(keys, best):
            lo, hi = self.bounds[k]
            v = lo + float(u) * (hi - lo)
            config[k] = type(config.get(k, v))(v) \
                if isinstance(config.get(k), (int, float)) else v
        return config
