"""The Tune control loop.

(ref: python/ray/tune/execution/tune_controller.py:68 TuneController — an
event-driven loop that creates trial actors, collects their results, asks the
scheduler for a decision per result, and the searcher for new configs.)

Each trial runs as a ``_TrainableActor`` — an actor holding the user's
Trainable; one ``train.remote()`` per iteration (ref: Trainable.train per-step
contract).  PBT exploits restart the victim actor from the donor's checkpoint
with a mutated config.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError, TaskError
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import FINISHED, Searcher
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.trainable import DONE, TRAINING_ITERATION, Trainable


@ray_tpu.remote
class _TrainableActor:
    """Hosts one Trainable instance (ref: Tune's trial actor — the Trainable
    itself is the actor in the reference; here it is wrapped so any class can
    ride on the generic actor runtime)."""

    def __init__(self, trainable_cls: type, config: Dict[str, Any],
                 trial_dir: str, trial_id: str, trial_name: str,
                 restore_from: Optional[str] = None):
        self._trainable: Trainable = trainable_cls(
            config=config, trial_dir=trial_dir, trial_id=trial_id,
            trial_name=trial_name)
        if restore_from:
            self._trainable.restore(restore_from)

    def train(self) -> Dict[str, Any]:
        return self._trainable.train()

    def save(self) -> str:
        return self._trainable.save()

    def restore(self, path: str) -> None:
        self._trainable.restore(path)

    def stop(self) -> None:
        self._trainable.stop()


class TuneController:
    """(ref: tune_controller.py:68; step loop :666)"""

    def __init__(
        self,
        trainable_cls: type,
        searcher: Searcher,
        scheduler: Optional[TrialScheduler] = None,
        experiment_path: str = "",
        experiment_name: str = "tune",
        metric: Optional[str] = None,
        mode: str = "max",
        stop: Optional[Dict[str, Any]] = None,
        max_concurrent_trials: Optional[int] = None,
        max_failures: int = 0,
        trial_resources: Optional[Dict[str, float]] = None,
        checkpoint_frequency: int = 0,
        checkpoint_at_end: bool = False,
        callbacks: Optional[List] = None,
        time_budget_s: Optional[float] = None,
        snapshot_fn: Optional[Callable[[List["Trial"]], None]] = None,
        snapshot_period_s: float = 10.0,
        restore_checkpoints: Optional[Dict[str, List[str]]] = None,
    ):
        self.trainable_cls = trainable_cls
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.stop_criteria = stop or {}
        self.max_failures = max_failures
        self.trial_resources = trial_resources or {"CPU": 1.0}
        self.experiment_path = experiment_path
        self.experiment_name = experiment_name
        self.checkpoint_frequency = checkpoint_frequency
        self.checkpoint_at_end = checkpoint_at_end
        self.callbacks = callbacks or []
        self.time_budget_s = time_budget_s
        #: Periodic experiment-state writer (ref: experiment_state.py) —
        #: makes a crash-interrupted run restorable via Tuner.restore.
        self.snapshot_fn = snapshot_fn
        self.snapshot_period_s = snapshot_period_s
        self._last_snapshot = 0.0
        #: config-json -> checkpoint path for restored trials.
        self.restore_checkpoints = restore_checkpoints or {}

        self.trials: List[Trial] = []
        self._searcher_done = False
        self._max_concurrent = max_concurrent_trials or self._fit_concurrency()
        self.scheduler.set_search_properties(metric, mode)

    def _fit_concurrency(self) -> int:
        """How many trials the cluster can host at once."""
        total = ray_tpu.cluster_resources()
        fits = []
        for key, need in self.trial_resources.items():
            if need > 0:
                fits.append(int(total.get(key, 0) / need))
        return max(1, min(fits) if fits else 4)

    # ------------------------------------------------------------- main loop
    def run(self) -> List[Trial]:
        deadline = (time.monotonic() + self.time_budget_s) if self.time_budget_s else None
        while True:
            self._maybe_create_trials()
            self._maybe_start_trials()
            live = [t for t in self.trials if t.status == Trial.RUNNING]
            if not live:
                if not any(t.status in (Trial.PENDING, Trial.PAUSED)
                           for t in self.trials):
                    break
                time.sleep(0.01)
                continue
            self._process_events(live)
            if (self.snapshot_fn is not None
                    and time.monotonic() - self._last_snapshot
                    > self.snapshot_period_s):
                self._last_snapshot = time.monotonic()
                try:
                    self.snapshot_fn(self.trials)
                except Exception:
                    pass  # snapshots must never kill the experiment
            if deadline and time.monotonic() > deadline:
                for t in live:
                    self._stop_trial(t, Trial.TERMINATED)
                break
        for cb in self.callbacks:
            if hasattr(cb, "on_experiment_end"):
                cb.on_experiment_end(trials=self.trials)
        return self.trials

    # --------------------------------------------------------- trial creation
    def _maybe_create_trials(self) -> None:
        while not self._searcher_done:
            active = sum(1 for t in self.trials
                         if t.status in (Trial.PENDING, Trial.RUNNING, Trial.PAUSED))
            if active >= self._max_concurrent * 2:
                return
            # The trial id is fixed BEFORE suggest() so searchers that key
            # per-trial state by the suggested id see the same id in every
            # later on_trial_result/on_trial_complete call.
            trial_id = uuid.uuid4().hex[:8]
            cfg = self.searcher.suggest(trial_id)
            if cfg is None or cfg == FINISHED:
                self._searcher_done = True
                return
            if cfg == "PENDING":  # ConcurrencyLimiter backpressure
                return
            trial = Trial(cfg, self.experiment_path, dict(self.trial_resources),
                          self.experiment_name, trial_id=trial_id)
            self.trials.append(trial)
            self.scheduler.on_trial_add(trial)
            for cb in self.callbacks:
                if hasattr(cb, "on_trial_start"):
                    cb.on_trial_start(trial=trial)

    def _maybe_start_trials(self) -> None:
        running = sum(1 for t in self.trials if t.status == Trial.RUNNING)
        pending = [t for t in self.trials if t.status == Trial.PENDING]
        budget = self._max_concurrent - running
        while budget > 0 and pending:
            trial = self.scheduler.choose_trial_to_run(pending)
            if trial is None:
                break
            pending.remove(trial)
            self._start_trial(trial)
            budget -= 1

    def _start_trial(self, trial: Trial, restore_from: Optional[str] = None) -> None:
        if restore_from is None and trial.checkpoint_path is None \
                and self.restore_checkpoints:
            # Experiment restore: resume this config from its recorded
            # checkpoint (keyed by config contents — trial ids are fresh;
            # duplicate configs pop their checkpoints in creation order).
            import json as _json

            key = _json.dumps(trial.config, sort_keys=True, default=str)
            ckpts = self.restore_checkpoints.get(key)
            if ckpts:
                # Persist on the trial so a retry after an early failure
                # restores from the SAME checkpoint instead of popping a
                # sibling's (or starting over).
                trial.checkpoint_path = ckpts.pop(0)
                restore_from = trial.checkpoint_path
        trial.actor = _TrainableActor.options(
            resources=trial.resources).remote(
            self.trainable_cls, trial.config, trial.logdir, trial.trial_id,
            trial.trial_name, restore_from or trial.checkpoint_path)
        trial.inflight = trial.actor.train.remote()
        trial.status = Trial.RUNNING

    # ------------------------------------------------------------ event pump
    def _process_events(self, live: List[Trial]) -> None:
        refs = [t.inflight for t in live]
        # Drain every ready trial this pump — taking only the first would let
        # list order starve the rest (ASHA needs rung records from all peers).
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0.2)
        if not ready:
            return
        by_ref = {t.inflight: t for t in live}
        for ref in ready:
            trial = by_ref[ref]
            try:
                result = ray_tpu.get(ref)
            except (TaskError, RayTpuError) as e:
                self._on_trial_error(trial, e)
                continue
            self._on_trial_result(trial, result)

    def _on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> None:
        trial.results.append(result)
        trial.last_result = result
        self.searcher.on_trial_result(trial.trial_id, result)
        for cb in self.callbacks:
            if hasattr(cb, "on_trial_result"):
                cb.on_trial_result(trial=trial, result=result)

        if result.get(DONE) or self._hit_stop_criteria(result):
            self._complete_trial(trial, result)
            return

        if (self.checkpoint_frequency
                and result.get(TRAINING_ITERATION, 0) % self.checkpoint_frequency == 0):
            try:
                trial.checkpoint_path = ray_tpu.get(trial.actor.save.remote())
            except (TaskError, RayTpuError):
                pass

        decision = self.scheduler.on_trial_result(trial, result)
        if decision == TrialScheduler.STOP:
            self._complete_trial(trial, result)
        elif decision == TrialScheduler.PAUSE and trial.pbt_exploit is not None:
            self._pbt_clone(trial)
        elif decision == TrialScheduler.PAUSE:
            self._pause_trial(trial)
        else:
            trial.inflight = trial.actor.train.remote()

    def _hit_stop_criteria(self, result: Dict[str, Any]) -> bool:
        for key, bound in self.stop_criteria.items():
            if callable(bound):
                if bound(result.get("trial_id", ""), result):
                    return True
            elif key in result and result[key] >= bound:
                return True
        return False

    def _on_trial_error(self, trial: Trial, error: BaseException) -> None:
        trial.num_failures += 1
        self._teardown_actor(trial)
        # max_failures < 0 means retry forever (FailureConfig contract;
        # matches train/trainer.py's handling of the same config).
        if self.max_failures < 0 or trial.num_failures <= self.max_failures:
            # retry from last checkpoint (ref: trial FSM retry w/ restore)
            trial.status = Trial.PENDING
            return
        trial.status = Trial.ERROR
        trial.error = error
        self.scheduler.on_trial_error(trial)
        self.searcher.on_trial_complete(trial.trial_id, error=True)
        for cb in self.callbacks:
            if hasattr(cb, "on_trial_error"):
                cb.on_trial_error(trial=trial, error=error)

    def _complete_trial(self, trial: Trial, result: Dict[str, Any]) -> None:
        if self.checkpoint_at_end:
            try:
                trial.checkpoint_path = ray_tpu.get(trial.actor.save.remote())
            except (TaskError, RayTpuError):
                pass
        self._stop_trial(trial, Trial.TERMINATED)
        self.scheduler.on_trial_complete(trial, result)
        self.searcher.on_trial_complete(trial.trial_id, result)
        for cb in self.callbacks:
            if hasattr(cb, "on_trial_complete"):
                cb.on_trial_complete(trial=trial, result=result)

    def _pause_trial(self, trial: Trial) -> None:
        try:
            trial.checkpoint_path = ray_tpu.get(trial.actor.save.remote())
        except (TaskError, RayTpuError):
            pass
        self._teardown_actor(trial)
        trial.status = Trial.PAUSED
        # PAUSED trials become PENDING again immediately — the scheduler
        # decides when to pick them back up via choose_trial_to_run.
        trial.status = Trial.PENDING

    def _pbt_clone(self, trial: Trial) -> None:
        """Exploit+explore: restart this trial from the donor's checkpoint
        with the mutated config (ref: pbt.py _exploit)."""
        payload, trial.pbt_exploit = trial.pbt_exploit, None
        donor: Trial = payload["donor"]
        try:
            donor_ckpt = ray_tpu.get(donor.actor.save.remote()) \
                if donor.actor is not None else donor.checkpoint_path
        except (TaskError, RayTpuError):
            donor_ckpt = donor.checkpoint_path
        self._teardown_actor(trial)
        trial.config = payload["new_config"]
        trial.checkpoint_path = donor_ckpt
        self._start_trial(trial, restore_from=donor_ckpt)

    def _stop_trial(self, trial: Trial, status: str) -> None:
        self._teardown_actor(trial)
        trial.status = status

    def _teardown_actor(self, trial: Trial) -> None:
        if trial.actor is not None:
            try:
                ray_tpu.get(trial.actor.stop.remote(), timeout=2.0)
            except (TaskError, RayTpuError, TimeoutError, Exception):
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
            trial.inflight = None
