"""Tuner — the experiment entry point (ref: python/ray/tune/tuner.py:44
Tuner, fit:344; tune/tune.py run for the legacy API)."""

from __future__ import annotations

import inspect
import json
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Union

import ray_tpu
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    """(ref: tune/tune_config.py TuneConfig)"""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    time_budget_s: Optional[float] = None
    trial_resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})


class Tuner:
    """(ref: tuner.py:44)"""

    def __init__(
        self,
        trainable: Union[Callable, type],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_state: Optional[dict] = None
        self._restore_path: Optional[str] = None

    # ------------------------------------------------------------- restore
    @classmethod
    def restore(cls, path: str, trainable: Union[Callable, type],
                *, resume_errored: bool = True,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (ref: tuner.py Tuner.restore / tune/execution/experiment_state.py).

        Finished trials are carried through as results; unfinished (and,
        with ``resume_errored``, errored) trials re-run with their recorded
        configs, restoring from their last checkpoint when one exists.
        """
        state_file = os.path.join(path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        tuner = cls(trainable, tune_config=tune_config, run_config=run_config)
        tuner._restore_state = state
        tuner._restore_path = path
        tuner._resume_errored = resume_errored
        return tuner

    def _fit_restored(self) -> ResultGrid:
        from ray_tpu.tune.trial import Trial

        tc = self.tune_config
        state = self._restore_state
        done_trials = []
        to_resume = []  # (config, checkpoint, trial_id)
        for t in state["trials"]:
            if t["status"] == Trial.TERMINATED:
                trial = Trial(t["config"], self._restore_path, {},
                              trial_id=t["trial_id"])
                trial.status = Trial.TERMINATED
                trial.last_result = t["last_result"]
                trial.checkpoint_path = t.get("checkpoint")
                done_trials.append(trial)
            elif t["status"] == Trial.ERROR and not self._resume_errored:
                continue
            else:
                to_resume.append((t["config"], t.get("checkpoint"),
                                  t["trial_id"]))
        resumed: list = []
        if to_resume:
            searcher = _ReplaySearcher([c for c, _, _ in to_resume])
            searcher.set_search_properties(tc.metric, tc.mode, {})
            controller = TuneController(
                trainable_cls=self._as_trainable_cls(self.trainable),
                searcher=searcher,
                scheduler=tc.scheduler or FIFOScheduler(),
                experiment_path=self._restore_path,
                experiment_name=os.path.basename(self._restore_path),
                metric=tc.metric, mode=tc.mode,
                stop=self.run_config.stop,
                max_concurrent_trials=tc.max_concurrent_trials,
                max_failures=self.run_config.failure_config.max_failures,
                trial_resources=dict(tc.trial_resources),
                time_budget_s=tc.time_budget_s,
                callbacks=self.run_config.callbacks,
                restore_checkpoints=_checkpoints_by_config(to_resume),
                # A resumed run must itself stay crash-resumable.
                snapshot_fn=lambda trials: self._save_experiment_state(
                    self._restore_path, done_trials + list(trials)),
            )
            resumed = controller.run()
        trials = done_trials + list(resumed)
        self._save_experiment_state(self._restore_path, trials)
        return ResultGrid(trials, tc.metric, tc.mode)

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if self._restore_state is not None:
            return self._fit_restored()
        tc = self.tune_config
        name = self.run_config.name or f"tune_{int(time.time())}"
        storage = self.run_config.storage_path or tempfile.mkdtemp(prefix="ray_tpu_tune_")
        experiment_path = os.path.join(storage, name)
        os.makedirs(experiment_path, exist_ok=True)

        trainable_cls = self._as_trainable_cls(self.trainable)
        # The trial actor is a lightweight controller; a Trainer inside a
        # trial reserves its own worker placement group (ref: Tune trial for a
        # Trainer requests the trainer's PG, workers request the rest).
        resources = dict(tc.trial_resources)
        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples)
        searcher.set_search_properties(tc.metric, tc.mode, self.param_space)

        controller = TuneController(
            trainable_cls=trainable_cls,
            searcher=searcher,
            scheduler=tc.scheduler or FIFOScheduler(),
            experiment_path=experiment_path,
            experiment_name=name,
            metric=tc.metric,
            mode=tc.mode,
            stop=self.run_config.stop,
            max_concurrent_trials=tc.max_concurrent_trials,
            max_failures=self.run_config.failure_config.max_failures,
            trial_resources=resources,
            time_budget_s=tc.time_budget_s,
            callbacks=self.run_config.callbacks,
            # Periodic snapshots make the experiment restorable after a crash
            # (ref: experiment_state.py periodic checkpointing).
            snapshot_fn=lambda trials: self._save_experiment_state(
                experiment_path, trials),
        )
        trials = controller.run()
        self._save_experiment_state(experiment_path, trials)
        return ResultGrid(trials, tc.metric, tc.mode)

    @staticmethod
    def _as_trainable_cls(trainable) -> type:
        if inspect.isclass(trainable) and issubclass(trainable, Trainable):
            return trainable
        if callable(trainable):
            return wrap_function(trainable)
        raise TypeError(f"Not a trainable: {trainable!r}")

    def _save_experiment_state(self, experiment_path: str, trials) -> None:
        """Experiment snapshot for post-hoc analysis
        (ref: tune/execution/experiment_state.py checkpoints)."""
        state = {
            "timestamp": time.time(),
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "status": t.status,
                    "config": _json_safe(t.config),
                    "last_result": _json_safe(t.last_result or {}),
                    "logdir": t.logdir,
                    "checkpoint": t.checkpoint_path,
                    "error": repr(t.error) if t.error else None,
                }
                for t in trials
            ],
        }
        # Atomic write: the periodic snapshot exists to survive crashes, so
        # a crash mid-dump must never corrupt the previous valid snapshot.
        final = os.path.join(experiment_path, "experiment_state.json")
        tmp = final + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, final)


def _checkpoints_by_config(to_resume) -> Dict[str, list]:
    """config-json -> [checkpoints, in original trial order].  A LIST per
    key because identical configs (num_samples>1 over a constant space) are
    distinct trials with distinct checkpoints; the controller pops in trial
    creation order so each resumed trial gets its own state back."""
    out: Dict[str, list] = {}
    for c, ckpt, _ in to_resume:
        if ckpt:
            out.setdefault(json.dumps(c, sort_keys=True, default=str),
                           []).append(ckpt)
    return out


class _ReplaySearcher(Searcher):
    """Feeds a fixed list of configs (experiment restore)."""

    def __init__(self, configs):
        self._configs = list(configs)
        self._i = 0

    def set_search_properties(self, metric, mode, param_space) -> bool:
        return True

    def suggest(self, trial_id: str):
        if self._i >= len(self._configs):
            from ray_tpu.tune.search import FINISHED

            return FINISHED
        cfg = self._configs[self._i]
        self._i += 1
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        pass


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        metric: Optional[str] = None, mode: str = "max", num_samples: int = 1,
        stop: Optional[Dict[str, Any]] = None, search_alg=None, scheduler=None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        max_concurrent_trials: Optional[int] = None,
        time_budget_s: Optional[float] = None,
        storage_path: Optional[str] = None, name: Optional[str] = None,
        max_failures: int = 0, verbose: int = 0,
        callbacks: Optional[list] = None) -> ResultGrid:
    """Legacy entry point (ref: tune/tune.py run)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    name = name or f"tune_{int(time.time())}"
    storage = storage_path or tempfile.mkdtemp(prefix="ray_tpu_tune_")
    experiment_path = os.path.join(storage, name)
    os.makedirs(experiment_path, exist_ok=True)
    trainable_cls = Tuner._as_trainable_cls(trainable)
    searcher = search_alg or BasicVariantGenerator(config or {}, num_samples=num_samples)
    searcher.set_search_properties(metric, mode, config or {})
    controller = TuneController(
        trainable_cls=trainable_cls, searcher=searcher,
        scheduler=scheduler or FIFOScheduler(),
        experiment_path=experiment_path, experiment_name=name,
        metric=metric, mode=mode, stop=stop,
        max_concurrent_trials=max_concurrent_trials, max_failures=max_failures,
        callbacks=callbacks,
        trial_resources=resources_per_trial or {"CPU": 1.0},
        time_budget_s=time_budget_s)
    trials = controller.run()
    return ResultGrid(trials, metric, mode)


def _json_safe(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in (d or {}).items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = repr(v)
    return out
