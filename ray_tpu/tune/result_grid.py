"""ResultGrid: the return value of Tuner.fit()
(ref: python/ray/tune/result_grid.py ResultGrid — per-trial Result access,
get_best_result)."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.trainer import Result
from ray_tpu.tune.trial import Trial


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max"):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._trials)

    def __getitem__(self, i: int) -> Result:
        return self._to_result(self._trials[i])

    def __iter__(self):
        return (self._to_result(t) for t in self._trials)

    @property
    def errors(self) -> List[BaseException]:
        return [t.error for t in self._trials if t.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return sum(1 for t in self._trials if t.status == Trial.TERMINATED)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("Pass metric= or set TuneConfig(metric=...)")
        # Rank by each trial's LAST report (ref: ResultGrid scope="last"
        # default) so the ranking agrees with the Result.metrics returned.
        scored = [((t.last_result or {}).get(metric), t) for t in self._trials]
        scored = [(s, t) for s, t in scored if s is not None]
        if not scored:
            raise RuntimeError("No trial reported the metric "
                               f"{metric!r}; errors: {self.errors}")
        best = max(scored, key=lambda st: st[0]) if mode == "max" \
            else min(scored, key=lambda st: st[0])
        return self._to_result(best[1])

    def get_dataframe(self):
        """Last-result table; requires pandas (present via jax deps)."""
        import pandas as pd

        rows = []
        for t in self._trials:
            row = dict(t.last_result or {})
            row.pop("config", None)
            for k, v in t.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)

    def _to_result(self, trial: Trial) -> Result:
        ckpt = Checkpoint(trial.checkpoint_path) if trial.checkpoint_path else None
        return Result(metrics=trial.last_result, checkpoint=ckpt,
                      path=trial.logdir, error=trial.error,
                      metrics_history=list(trial.results))
