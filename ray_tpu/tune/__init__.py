"""ray_tpu.tune — hyperparameter tuning (ref: python/ray/tune/).

Surface: Tuner/TuneConfig/run, search-space constructors, searchers,
ASHA/PBT/median-stopping schedulers, Trainable class + function APIs, and a
``tune.report`` that shares the Train session plumbing (in the reference both
route through ray.train's session since 2.x).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.train import checkpoint as _ckpt
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig
from ray_tpu.train.session import get_session
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    HyperOptStyleSearcher,
    RandomSearch,
    Searcher,
)
from ray_tpu.tune.search_space import (
    choice,
    grid_search,
    lograndint,
    loguniform,
    qloguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import Trainable, with_parameters
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tuner import TuneConfig, Tuner, run

Checkpoint = _ckpt.Checkpoint

__all__ = [
    "Tuner", "TuneConfig", "run", "Trainable", "with_parameters", "report",
    "get_checkpoint", "Searcher", "BasicVariantGenerator", "RandomSearch",
    "ConcurrencyLimiter", "HyperOptStyleSearcher", "TrialScheduler",
    "FIFOScheduler", "ASHAScheduler", "MedianStoppingRule",
    "PB2", "PopulationBasedTraining", "ResultGrid", "Trial", "Checkpoint",
    "RunConfig", "FailureConfig", "CheckpointConfig",
    "uniform", "quniform", "loguniform", "qloguniform", "randn", "randint",
    "qrandint", "lograndint", "choice", "sample_from", "grid_search",
]


def report(metrics: Optional[Dict[str, Any]] = None,
           checkpoint: Optional[Checkpoint] = None, **kwargs: Any) -> None:
    """Report metrics (+ optional checkpoint) from a function trainable.

    Accepts both the modern ``tune.report({"loss": x})`` and the legacy
    kwargs form ``tune.report(loss=x)`` (ref: tune's report in
    train/_internal/session.py:672 and legacy tune/trainable/session.py).
    """
    merged = dict(metrics or {})
    merged.update(kwargs)
    session = get_session()
    if session is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    session.report(merged, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    session = get_session()
    return session.checkpoint_to_restore if session else None
