"""Trainable: class API + function-API wrapper.

(ref: python/ray/tune/trainable/trainable.py:58 Trainable — setup/step/
save_checkpoint/load_checkpoint with train() bookkeeping; function API wrapped
by tune/trainable/function_trainable.py FunctionTrainable — user fn runs in a
thread, reporting through the session queue.)
"""

from __future__ import annotations

import inspect
import os
import tempfile
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, TrainSession, clear_session, init_session

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Subclass API (ref: trainable.py:58)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 trial_dir: Optional[str] = None, trial_id: str = "",
                 trial_name: str = ""):
        self.config = config or {}
        self.trial_id = trial_id
        self.trial_name = trial_name
        self._trial_dir = trial_dir or tempfile.mkdtemp(prefix="ray_tpu_trial_")
        os.makedirs(self._trial_dir, exist_ok=True)
        self.iteration = 0
        self._start_time = time.time()
        self.setup(self.config)

    # -------- subclass hooks
    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return None

    def load_checkpoint(self, checkpoint: Optional[Dict], checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    # -------- controller-facing API
    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self.iteration += 1
        result.setdefault(TRAINING_ITERATION, self.iteration)
        result.setdefault("trial_id", self.trial_id)
        result.setdefault("time_total_s", time.time() - self._start_time)
        result.setdefault("timestamp", time.time())
        result.setdefault("config", self.config)
        return result

    def save(self) -> str:
        ckpt_dir = os.path.join(self._trial_dir,
                                f"checkpoint_{self.iteration:06d}")
        os.makedirs(ckpt_dir, exist_ok=True)
        data = self.save_checkpoint(ckpt_dir)
        import pickle

        # Pickle (not JSON) so arbitrary checkpoint values round-trip
        # faithfully; iteration rides along so a restored trial resumes its
        # training_iteration clock (ref: Trainable persists _iteration).
        with open(os.path.join(ckpt_dir, "trainable_state.pkl"), "wb") as f:
            pickle.dump({"data": data, "iteration": self.iteration}, f)
        return ckpt_dir

    def restore(self, checkpoint_path: str) -> None:
        data = None
        state_file = os.path.join(checkpoint_path, "trainable_state.pkl")
        if os.path.exists(state_file):
            import pickle

            with open(state_file, "rb") as f:
                state = pickle.load(f)
            data = state["data"]
            self.iteration = state.get("iteration", self.iteration)
        self.load_checkpoint(data, checkpoint_path)

    def stop(self) -> None:
        self.cleanup()

    def logdir(self) -> str:
        return self._trial_dir


class FunctionTrainable(Trainable):
    """Wraps ``def train_fn(config)`` into the Trainable contract
    (ref: function_trainable.py — fn runs in a thread; each tune.report()
    produces one train() result)."""

    _fn: Callable = None  # bound by wrap_function's subclass

    def setup(self, config: Dict[str, Any]) -> None:
        ctx = TrainContext(world_rank=0, world_size=1, local_rank=0,
                           trial_name=self.trial_name or self.trial_id)
        self._session = TrainSession(ctx, checkpoint_to_restore=None)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._finished = threading.Event()
        self._last_checkpoint: Optional[Checkpoint] = None

    def _runner(self) -> None:
        init_session(self._session)
        try:
            params = inspect.signature(type(self)._fn).parameters
            if len(params) >= 1:
                type(self)._fn(self.config)
            else:
                type(self)._fn()
        except StopIteration:
            pass
        except BaseException as e:  # surfaced on the next train() call
            self._error = e
            self._error_tb = traceback.format_exc()
        finally:
            clear_session()
            self._finished.set()

    def train(self) -> Dict[str, Any]:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True,
                                            name=f"trial-{self.trial_id}")
            self._thread.start()
        # Block until the fn reports, or finishes.
        while True:
            try:
                item = self._session.results.get(timeout=0.05)
                break
            except Exception:
                if self._finished.is_set() and self._session.results.empty():
                    if self._error is not None:
                        raise self._error
                    item = {"metrics": {DONE: True}, "checkpoint": None, "rank": 0}
                    break
        metrics = dict(item["metrics"])
        if item["checkpoint"] is not None:
            self._last_checkpoint = item["checkpoint"]
        self.iteration += 1
        metrics.setdefault(TRAINING_ITERATION, self.iteration)
        metrics.setdefault("trial_id", self.trial_id)
        metrics.setdefault("time_total_s", time.time() - self._start_time)
        metrics.setdefault("config", self.config)
        if self._finished.is_set() and self._session.results.empty():
            metrics.setdefault(DONE, True)
        return metrics

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        if self._last_checkpoint is not None:
            import shutil

            for name in os.listdir(self._last_checkpoint.path):
                src = os.path.join(self._last_checkpoint.path, name)
                dst = os.path.join(checkpoint_dir, name)
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True)
                else:
                    shutil.copy2(src, dst)
        return None

    def load_checkpoint(self, data, checkpoint_dir: str) -> None:
        self._session.checkpoint_to_restore = Checkpoint(checkpoint_dir)

    def stop(self) -> None:
        self._session.stop_requested.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.cleanup()


def wrap_function(fn: Callable) -> type:
    """Create a FunctionTrainable subclass bound to ``fn``."""

    class _Wrapped(FunctionTrainable):
        _fn = staticmethod(fn)

    _Wrapped.__name__ = getattr(fn, "__name__", "fn")
    return _Wrapped


def with_parameters(trainable: Callable, **params: Any) -> Callable:
    """Bind large objects to a trainable outside the config dict
    (ref: tune/trainable/util.py with_parameters)."""
    if inspect.isclass(trainable):
        class _WithParams(trainable):  # type: ignore[misc]
            def setup(self, config):
                merged = dict(config)
                merged.update(params)
                super().setup(merged)

        _WithParams.__name__ = trainable.__name__
        return _WithParams

    def _fn(config):
        sig = inspect.signature(trainable)
        if len(sig.parameters) > 1:
            return trainable(config, **params)
        merged = dict(config)
        merged.update(params)
        return trainable(merged)

    _fn.__name__ = getattr(trainable, "__name__", "fn")
    return _fn
