"""Searchers (ref: python/ray/tune/search/ — searcher.py Searcher,
basic_variant.py BasicVariantGenerator, concurrency_limiter ConcurrencyLimiter).

A Searcher hands out concrete configs; the controller feeds results back so
adaptive searchers can condition future suggestions.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search_space import Domain, expand_grid, resolve

FINISHED = "FINISHED"  # sentinel: searcher exhausted


class Searcher:
    """(ref: tune/search/searcher.py Searcher)"""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random draws
    (ref: tune/search/basic_variant.py:109 BasicVariantGenerator)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None, num_samples: int = 1,
                 seed: Optional[int] = None, points_to_evaluate: Optional[List[Dict]] = None):
        super().__init__()
        self._space = space or {}
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._queue: List[Dict[str, Any]] = list(points_to_evaluate or [])
        self._grid = expand_grid(self._space)
        self._emitted = 0
        self._total = len(self._grid) * num_samples + len(self._queue)

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if config:
            self._space = config
            self._grid = expand_grid(config)
            self._total = len(self._grid) * self._num_samples + len(self._queue)
        return True

    @property
    def total_samples(self) -> int:
        return self._total

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._queue:
            return resolve(self._queue.pop(0), self._rng)
        if self._emitted >= len(self._grid) * self._num_samples:
            return None
        variant = self._grid[self._emitted % len(self._grid)]
        self._emitted += 1
        return resolve(variant, self._rng)


class RandomSearch(BasicVariantGenerator):
    """Pure random sampling over the space (grid leaves sampled uniformly too)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        flat = {
            k: (v if not (isinstance(v, dict) and set(v) == {"grid_search"})
                else _grid_to_choice(v))
            for k, v in space.items()
        }
        super().__init__(flat, num_samples=num_samples, seed=seed)


def _grid_to_choice(v: Dict[str, Any]) -> Domain:
    from ray_tpu.tune.search_space import Categorical

    return Categorical(v["grid_search"])


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (ref: tune/search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return "PENDING"  # backpressure marker understood by controller
        cfg = self.searcher.suggest(trial_id)
        if isinstance(cfg, dict):
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class HyperOptStyleSearcher(Searcher):
    """A dependency-free adaptive searcher: random exploration that narrows
    around the best-seen configs (TPE-flavored exploitation without hyperopt).
    Stands in for the reference's hyperopt/optuna integrations
    (ref: tune/search/hyperopt/, tune/search/optuna/) since neither package
    ships in this environment.
    """

    def __init__(self, space: Dict[str, Any], metric: str, mode: str = "max",
                 num_samples: int = 1, seed: Optional[int] = None,
                 explore_fraction: float = 0.5):
        super().__init__(metric, mode)
        self._space = space
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._emitted = 0
        self._observations: List[tuple] = []  # (score, config)
        self._explore_fraction = explore_fraction
        self._grid = expand_grid(space)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._emitted >= self._num_samples:
            return None
        self._emitted += 1
        base = self._grid[self._rng.randrange(len(self._grid))]
        if len(self._observations) < 3 or self._rng.random() < self._explore_fraction:
            return resolve(base, self._rng)
        # Exploit: jitter around a top-quartile config.
        ranked = sorted(self._observations, key=lambda t: t[0],
                        reverse=(self.mode == "max"))
        top = ranked[: max(1, len(ranked) // 4)]
        _, anchor = top[self._rng.randrange(len(top))]
        out = {}
        for k, v in base.items():
            if isinstance(v, Domain) and k in anchor and isinstance(anchor[k], (int, float)):
                jittered = anchor[k] * self._rng.uniform(0.8, 1.25)
                out[k] = type(anchor[k])(jittered)
            elif isinstance(v, Domain):
                out[k] = v.sample(self._rng)
            else:
                out[k] = v
        return out

    def on_trial_complete(self, trial_id, result=None, error=False):
        if result and not error and self.metric in result:
            self._observations.append((float(result[self.metric]),
                                       {k: v for k, v in result.get("config", {}).items()}))
