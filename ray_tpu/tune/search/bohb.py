"""BOHB adapter: TuneBOHB searcher + HyperBandForBOHB scheduler (ref:
python/ray/tune/search/bohb/bohb_search.py TuneBOHB +
tune/schedulers/hb_bohb.py HyperBandForBOHB).

The searcher is a graceful-import shell over ConfigSpace (the library BOHB
defines its spaces in): without ConfigSpace it raises a clear ImportError
at construction; with it (or any module exposing the same
ConfigurationSpace surface) our Domains convert to CS hyperparameters and
suggestions come from ``sample_configuration``, model-weighted by the
top-performing completions so far (the BOHB KDE role, reduced to a
sample-and-rank step that needs no hpbandster).

HyperBandForBOHB is real and dependency-free: successive-halving brackets
over the report budget, pausing the bottom fraction at each rung — the
scheduler half of BOHB, usable with ANY searcher.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import Searcher
from ray_tpu.tune.search_space import Categorical, Domain, Float, Integer


def _import_configspace():
    try:
        import ConfigSpace  # noqa: F401

        return ConfigSpace
    except ImportError as e:
        raise ImportError(
            "TuneBOHB requires the `ConfigSpace` package, which is not "
            "installed in this environment (pip install ConfigSpace)."
        ) from e


class TuneBOHB(Searcher):
    """ConfigSpace-backed model-lite BOHB searcher shell."""

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", seed: Optional[int] = None,
                 top_fraction: float = 0.3, _configspace_module=None):
        super().__init__(metric=metric, mode=mode)
        cs = _configspace_module or _import_configspace()
        self._cs_space = cs.ConfigurationSpace(seed=seed)
        self._fixed: Dict[str, Any] = {}
        for name, dom in space.items():
            if isinstance(dom, Float):
                hp = cs.UniformFloatHyperparameter(
                    name, lower=dom.lower, upper=dom.upper, log=dom.log)
            elif isinstance(dom, Integer):
                # Native Integer uppers are EXCLUSIVE; ConfigSpace's is
                # inclusive.
                hp = cs.UniformIntegerHyperparameter(
                    name, lower=dom.lower, upper=dom.upper - 1)
            elif isinstance(dom, Categorical):
                hp = cs.CategoricalHyperparameter(name, list(dom.categories))
            elif isinstance(dom, Domain):
                raise TypeError(
                    f"TuneBOHB cannot convert domain {type(dom).__name__} "
                    f"for {name!r}")
            else:
                self._fixed[name] = dom
                continue
            self._cs_space.add(hp) if hasattr(self._cs_space, "add") \
                else self._cs_space.add_hyperparameter(hp)
        self._top_fraction = top_fraction
        self._completed: List[tuple] = []  # (score, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        # BOHB-lite: draw a handful of candidates; past the warmup, pick
        # the one nearest (L0 over categoricals / normalized L1 elsewhere)
        # to a random member of the top fraction — the KDE "model" reduced
        # to sample-and-rank, which needs no hpbandster.
        candidates = [dict(self._cs_space.sample_configuration())
                      for _ in range(8)]
        pick = candidates[0]
        if len(self._completed) >= 4:
            sign = 1.0 if self.mode == "max" else -1.0
            ranked = sorted(self._completed, key=lambda t: -sign * t[0])
            top = ranked[:max(1, int(len(ranked) * self._top_fraction))]
            anchor = top[len(self._completed) % len(top)][1]
            pick = min(candidates, key=lambda c: self._distance(c, anchor))
        return {**self._fixed, **pick}

    @staticmethod
    def _distance(a: Dict[str, Any], b: Dict[str, Any]) -> float:
        d = 0.0
        for k, va in a.items():
            vb = b.get(k)
            if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
                scale = max(abs(va), abs(vb), 1e-9)
                d += abs(va - vb) / scale
            else:
                d += 0.0 if va == vb else 1.0
        return d

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        if error or not result or self.metric not in result:
            return
        cfg = {k: v for k, v in result.get("config", {}).items()}
        self._completed.append((float(result[self.metric]), cfg))


class HyperBandForBOHB(TrialScheduler):
    """Successive-halving brackets over the report budget (ref:
    tune/schedulers/hb_bohb.py) — pause-and-resume-free reduction: at each
    rung, trials below the top 1/reduction_factor quantile STOP."""

    def __init__(self, metric: str, mode: str = "max",
                 max_t: int = 100, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        self.time_attr = time_attr
        # Integer division, not int(math.log(max_t, rf)): the float log of an
        # exact power (log(9, 3)) can land just under the integer and silently
        # drop the lowest rung.
        rungs = set()
        r = max_t
        while r > 1:
            rungs.add(r)
            r //= reduction_factor
        self._rungs = sorted(rungs or {max_t})
        self._rung_scores: Dict[int, List[float]] = {r: [] for r in self._rungs}
        #: (trial identity, rung) -> signed score recorded ONCE per rung;
        #: later reports re-evaluate against the (growing) rung population,
        #: so an early reporter that snuck past a not-yet-quorate rung is
        #: still cut on its next report once the cutoff exists.
        self._recorded: Dict[tuple, float] = {}
        #: id()-keyed trials pinned alive: a freed trial's id can be reused
        #: by a NEW trial, which would then inherit the dead one's rung
        #: records and dodge the cutoff.
        self._anon_trials: Dict[int, Any] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        score = result.get(self.metric)
        if score is None:
            return TrialScheduler.CONTINUE
        if t >= self.max_t:
            return TrialScheduler.STOP
        sign = 1.0 if self.mode == "max" else -1.0
        rung = max((r for r in self._rungs if r <= t), default=None)
        if rung is None:
            return TrialScheduler.CONTINUE
        tid = getattr(trial, "trial_id", None)
        if tid is None:
            tid = id(trial)
            self._anon_trials[tid] = trial
        key = (tid, rung)
        if key not in self._recorded:
            self._recorded[key] = sign * score
            self._rung_scores[rung].append(sign * score)
        scores = self._rung_scores[rung]
        if len(scores) >= self.rf:
            keep = max(1, len(scores) // self.rf)
            cutoff = sorted(scores, reverse=True)[keep - 1]
            if self._recorded[key] < cutoff:
                return TrialScheduler.STOP
        return TrialScheduler.CONTINUE
