"""Optuna searcher adapter (ref: python/ray/tune/search/optuna/
optuna_search.py:81 OptunaSearch).

Graceful-import shell (the pattern proven by air/integrations/wandb.py):
constructing the adapter without optuna installed raises a clear
ImportError naming the dependency; with optuna (or any module exposing the
same ask/tell study surface) present, suggestions come from
``study.ask()`` with our Domain objects converted to optuna distributions,
and completions feed back through ``study.tell``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search import Searcher
from ray_tpu.tune.search_space import Categorical, Domain, Float, Integer


def _import_optuna():
    try:
        import optuna  # noqa: F401

        return optuna
    except ImportError as e:
        raise ImportError(
            "OptunaSearch requires the `optuna` package, which is not "
            "installed in this environment (pip install optuna)."
        ) from e


class OptunaSearch(Searcher):
    """Ask/tell bridge onto an optuna Study.

    space: {name: Domain | fixed value} — the same search-space dicts the
    native searchers take; Float/Integer/Categorical map to
    suggest_float/suggest_int/suggest_categorical.
    """

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", seed: Optional[int] = None,
                 study: Optional[Any] = None, _optuna_module=None):
        super().__init__(metric=metric, mode=mode)
        optuna = _optuna_module or _import_optuna()
        self._optuna = optuna
        self._space = space
        if study is not None:
            self._study = study
        else:
            sampler = optuna.samplers.TPESampler(seed=seed)
            self._study = optuna.create_study(
                direction="maximize" if mode == "max" else "minimize",
                sampler=sampler)
        self._trials: Dict[str, Any] = {}  # tune trial_id -> optuna trial

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        ot = self._study.ask()
        self._trials[trial_id] = ot
        config = {}
        for name, dom in self._space.items():
            if isinstance(dom, Float):
                config[name] = ot.suggest_float(name, dom.lower, dom.upper,
                                                log=dom.log)
            elif isinstance(dom, Integer):
                # Native Integer uppers are EXCLUSIVE (search_space.py);
                # optuna's high is inclusive.
                config[name] = ot.suggest_int(name, dom.lower,
                                              dom.upper - 1, log=dom.log)
            elif isinstance(dom, Categorical):
                config[name] = ot.suggest_categorical(name, list(dom.categories))
            elif isinstance(dom, Domain):
                raise TypeError(
                    f"OptunaSearch cannot convert domain {type(dom).__name__}"
                    f" for {name!r}")
            else:
                config[name] = dom  # fixed value
        return config

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=self._failed_state())
            return
        self._study.tell(ot, float(result[self.metric]))

    def _failed_state(self):
        try:
            return self._optuna.trial.TrialState.FAIL
        except AttributeError:
            return "FAIL"
