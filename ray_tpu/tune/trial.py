"""Trial: one hyperparameter configuration's lifecycle
(ref: python/ray/tune/experiment/trial.py:248 Trial — status FSM, config,
checkpoint bookkeeping, resources)."""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"

    def __init__(self, config: Dict[str, Any], experiment_path: str,
                 trial_resources: Optional[Dict[str, float]] = None,
                 experiment_name: str = "", trial_id: Optional[str] = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.status = Trial.PENDING
        self.resources = trial_resources or {"CPU": 1.0}
        self.experiment_name = experiment_name
        self.trial_name = f"{experiment_name}_{self.trial_id}"
        self.logdir = os.path.join(experiment_path, self.trial_name)
        os.makedirs(self.logdir, exist_ok=True)

        self.results: List[Dict[str, Any]] = []
        self.last_result: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None
        self.num_failures = 0
        self.checkpoint_path: Optional[str] = None  # latest saved checkpoint dir
        # PBT exploit payload set by the scheduler (donor trial + new config).
        self.pbt_exploit: Optional[Dict[str, Any]] = None

        # runtime handles (controller-owned)
        self.actor = None
        self.inflight = None  # ObjectRef of the outstanding train() call

    def best_metric(self, metric: str, mode: str) -> Optional[float]:
        vals = [r[metric] for r in self.results if metric in r]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)

    def __repr__(self) -> str:
        return f"Trial({self.trial_id}, {self.status}, config={self.config})"
