"""Search-space primitives (ref: python/ray/tune/search/sample.py —
Domain/Float/Integer/Categorical; grid_search in tune/search/variant_generator.py).

A param_space is a (possibly nested) dict whose leaves may be Domains or
``grid_search(...)`` markers.  Grid leaves are expanded as a cross product;
Domain leaves are sampled per trial.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    """Base class for samplable hyperparameter domains."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False, q: float = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math

            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return min(max(v, self.lower), self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False, q: int = 1):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> int:
        # Upper bound is EXCLUSIVE on both paths (ref: tune randint/lograndint
        # contract), so e.g. lograndint(0, len(xs)) is a safe index.
        hi = self.upper - 1 if self.upper > self.lower else self.lower
        if self.log:
            import math

            v = int(round(math.exp(rng.uniform(math.log(max(self.lower, 1)),
                                               math.log(max(hi, 1))))))
        else:
            v = rng.randint(self.lower, hi)
        if self.q > 1:
            v = int(round(v / self.q) * self.q)
        return min(max(v, self.lower), hi)


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng: random.Random) -> float:
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        return self.fn()


# -------------------- public constructors (ref: tune.uniform & co) ----------

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    """(ref: tune/search/variant_generator.py grid_search)"""
    return {"grid_search": list(values)}


# -------------------- expansion helpers -------------------------------------

def _is_grid(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-product of every grid_search leaf; Domains left in place."""
    variants: List[Dict[str, Any]] = [{}]
    for key, value in space.items():
        if _is_grid(value):
            variants = [dict(v, **{key: g}) for v in variants for g in value["grid_search"]]
        elif isinstance(value, dict) and not _is_grid(value):
            subs = expand_grid(value)
            variants = [dict(v, **{key: s}) for v in variants for s in subs]
        else:
            variants = [dict(v, **{key: value}) for v in variants]
    return variants


def resolve(space: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    """Sample every Domain leaf, returning a concrete config."""
    out: Dict[str, Any] = {}
    for key, value in space.items():
        if isinstance(value, Domain):
            out[key] = value.sample(rng)
        elif isinstance(value, dict) and not _is_grid(value):
            out[key] = resolve(value, rng)
        else:
            out[key] = value
    return out
