"""Device mesh construction and named parallelism axes.

The reference has no native TP/PP/SP (SURVEY §2.3: delegated to DeepSpeed/HF
over Ray-provided process groups).  Here parallelism is first-class: a
``MeshSpec`` names the six standard axes and maps them onto the physical
device grid; shardings are expressed as PartitionSpecs over these names and
XLA inserts the collectives (psum for dp/fsdp grad sync, all-gather for fsdp
params, all-to-all/ppermute for sp) — the scaling-book recipe.

Axes (outermost → innermost = slowest → fastest links):
  pipe   — pipeline parallel (GPipe microbatch schedule, parallel/pipeline.py;
           stage handoffs are point-to-point ppermutes, so this axis tolerates
           the slowest links — put it across DCN on multi-slice)
  data   — pure data parallel (gradient psum)
  fsdp   — data parallel with parameter/optimizer sharding (ZeRO-3 equiv:
           XLA all-gathers params per layer, reduce-scatters grads)
  expert — expert parallel for MoE layers (token dispatch = all_to_all)
  tensor — megatron-style tensor parallel (activations psum)
  seq    — sequence/context parallel (ring attention / all-to-all)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_NAMES = ("pipe", "data", "fsdp", "expert", "tensor", "seq")


@dataclass(frozen=True)
class MeshSpec:
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    @property
    def size(self) -> int:
        return (self.pipe * self.data * self.fsdp * self.expert
                * self.tensor * self.seq)

    def axis_sizes(self) -> Dict[str, int]:
        return {"pipe": self.pipe, "data": self.data, "fsdp": self.fsdp,
                "expert": self.expert, "tensor": self.tensor, "seq": self.seq}

    @staticmethod
    def auto(n_devices: int, tensor: int = 1, seq: int = 1,
             fsdp: Optional[int] = None, pipe: int = 1,
             expert: int = 1) -> "MeshSpec":
        """Fill the data axis with whatever the other axes don't consume."""
        inner = tensor * seq * (fsdp or 1) * pipe * expert
        if n_devices % inner != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by "
                f"pipe*expert*tensor*seq*fsdp={inner}")
        return MeshSpec(data=n_devices // inner, fsdp=fsdp or 1,
                        tensor=tensor, seq=seq, pipe=pipe, expert=expert)


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a jax Mesh with the canonical axis order
    (pipe, data, fsdp, expert, tensor, seq).

    Device order matters on real hardware: JAX returns devices in
    topology-aware order, so the innermost axes (tensor, seq) land on
    ICI-adjacent chips, keeping the chattiest collectives on the shortest
    links — the analogue of the reference packing PG bundles onto one node.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    if spec.size > len(devices):
        raise ValueError(f"MeshSpec needs {spec.size} devices, have {len(devices)}")
    grid = np.array(devices[: spec.size]).reshape(
        spec.pipe, spec.data, spec.fsdp, spec.expert, spec.tensor, spec.seq)
    return jax.sharding.Mesh(grid, AXIS_NAMES)


def partition(*axes) -> "jax.sharding.PartitionSpec":  # noqa: F821
    import jax

    return jax.sharding.PartitionSpec(*axes)


def named_sharding(mesh, *axes):
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*axes))


def batch_sharding(mesh, rules: Optional[Dict] = None):
    """Sharding for a (batch, seq) token array — the one true place that
    encodes batch->(data,fsdp), seq->seq so call sites can't drift."""
    import jax

    return jax.sharding.NamedSharding(mesh, logical_to_spec(("batch", "seqlen"), rules))


# Logical axis rules: model code annotates params with logical axis names and
# these rules map them to mesh axes (the flax/t5x "logical axes" idea, kept
# dependency-free).
DEFAULT_RULES: Dict[str, Optional[Tuple[str, ...]]] = {
    "vocab": ("tensor",),
    "embed": ("fsdp",),
    "heads": ("tensor",),
    "kv": None,
    "mlp": ("tensor",),
    "batch": ("data", "fsdp"),
    "seqlen": ("seq",),
    "norm": None,
    # Leading stacked-layer axis of a pipelined block stack: sharding it over
    # `pipe` gives each stage its slice of layers (parallel/pipeline.py).
    "layers": ("pipe",),
    # Leading expert axis of MoE expert weights (models/moe.py).
    "expert": ("expert",),
}


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules: Optional[Dict] = None):
    """('vocab','embed') -> PartitionSpec(('tensor',), ('fsdp',))."""
    import jax

    rules = rules or DEFAULT_RULES
    spec = []
    for name in logical_axes:
        if name is None:
            spec.append(None)
            continue
        mapped = rules.get(name)
        if mapped is None:
            spec.append(None)
        elif len(mapped) == 1:
            spec.append(mapped[0])
        else:
            spec.append(tuple(mapped))
    return jax.sharding.PartitionSpec(*spec)


def shard_pytree(tree, logical_tree, mesh, rules: Optional[Dict] = None):
    """device_put a parameter pytree according to its logical axis pytree."""
    import jax

    def place(x, logical):
        return jax.device_put(x, jax.sharding.NamedSharding(mesh, logical_to_spec(logical, rules)))

    return jax.tree.map(place, tree, logical_tree)


def pytree_sharding(logical_tree, mesh, rules: Optional[Dict] = None):
    """NamedSharding pytree (for jit in_shardings/out_shardings)."""
    import jax

    def to_sharding(logical):
        return jax.sharding.NamedSharding(mesh, logical_to_spec(logical, rules))

    return jax.tree.map(to_sharding, logical_tree,
                        is_leaf=lambda x: isinstance(x, tuple) and all(
                            isinstance(a, (str, type(None))) for a in x))
