"""Sharded train-state assembly: params + optimizer state on a mesh.

The ZeRO/FSDP equivalent of the reference's Train stack (ref: train/torch/
train_loop_utils.py prepare_model DDP/FSDP wrap) with no wrapper at all:
parameters are placed with their logical shardings, optimizer state is
*computed from them under jit* so XLA propagates the same shardings onto the
Adam moments (optimizer sharding = ZeRO), and the train step is jitted with
donated state — gradient synchronization is derived by the partitioner, not
written by hand.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ray_tpu.parallel.mesh import pytree_sharding


def create_sharded_state(
    init_fn: Callable[[Any], Any],
    logical: Any,
    mesh,
    key,
    optimizer=None,
    rules: Optional[Dict] = None,
) -> Tuple[Any, Any]:
    """Initialize params directly into their sharded layout (no host round
    trip: init runs under jit with out_shardings so each device materializes
    only its shard) and derive optimizer state with propagated shardings."""
    shardings = pytree_sharding(logical, mesh, rules)
    from ray_tpu._private.jax_compat import set_mesh as _set_mesh

    with _set_mesh(mesh):
        params = jax.jit(init_fn, out_shardings=shardings)(key)
        opt_state = None
        if optimizer is not None:
            opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state


def jit_train_step(step_fn, donate_state: bool = True, mesh=None):
    """jit with donated (params, opt_state) so updates reuse their buffers —
    the HBM discipline that makes big models fit.

    Pass ``mesh`` when the model uses context-parallel attention
    (attn_impl="ring"/"ulysses"): those ops shard_map over the AMBIENT mesh,
    which this wrapper installs around trace/execute via jax.set_mesh.
    """
    donate = (0, 1) if donate_state else ()
    jitted = jax.jit(step_fn, donate_argnums=donate)
    if mesh is None:
        return jitted

    from ray_tpu._private.jax_compat import set_mesh as _set_mesh

    def call(*args, **kwargs):
        with _set_mesh(mesh):
            return jitted(*args, **kwargs)

    return call
