from ray_tpu.parallel.mesh import (
    AXIS_NAMES,
    DEFAULT_RULES,
    MeshSpec,
    batch_sharding,
    logical_to_spec,
    make_mesh,
    named_sharding,
    partition,
    pytree_sharding,
    shard_pytree,
)
from ray_tpu.parallel.pipeline import pipeline_apply

__all__ = [
    "AXIS_NAMES", "DEFAULT_RULES", "MeshSpec", "batch_sharding",
    "logical_to_spec", "make_mesh", "named_sharding", "partition",
    "pipeline_apply", "pytree_sharding", "shard_pytree",
]
