from ray_tpu.parallel.mesh import (
    AXIS_NAMES,
    DEFAULT_RULES,
    MeshSpec,
    batch_sharding,
    logical_to_spec,
    make_mesh,
    named_sharding,
    partition,
    pytree_sharding,
    shard_pytree,
)

__all__ = [
    "AXIS_NAMES", "DEFAULT_RULES", "MeshSpec", "batch_sharding",
    "logical_to_spec", "make_mesh", "named_sharding", "partition",
    "pytree_sharding", "shard_pytree",
]
