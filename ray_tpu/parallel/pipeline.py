"""Pipeline parallelism: GPipe microbatch schedule over the `pipe` mesh axis.

The reference has NO native pipeline parallelism (SURVEY §2.3 — PP arises only
inside integrated frameworks, or via Compiled Graph channels driven by external
engines like vLLM).  Here it is native and TPU-shaped: the whole pipeline is
ONE jitted SPMD program.  `jax.shard_map` is entered manually over only the
`pipe` axis (partial-manual; every other mesh axis stays auto so XLA keeps
sharding dp/fsdp/tensor/seq inside each stage), stage handoffs are
`lax.ppermute` point-to-point transfers that ride a single ICI/DCN hop, and
the microbatch loop is a `lax.scan`, so the schedule is reverse-mode
differentiable and the backward pipeline is derived by AD (scan + ppermute
transpose) rather than hand-scheduled.

Schedule: classic GPipe.  With S stages and M microbatches the loop runs
S+M-1 ticks; at tick t stage s computes microbatch t-s (bubble fraction
(S-1)/(S+M-1) — pick M >= 4*S to amortize).  All stages execute every tick
(SPMD), so the bubble costs FLOPs, not correctness.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

# jax imports are function-local, matching mesh.py: importing this package
# must not initialize jax (tests/conftest.py sets platform env first).

PIPE_AXIS = "pipe"


def _pipeline_local(stage_fn: Callable[[Any, Any], Any],
                    stage_params: Any,
                    x_mb,
                    *,
                    axis_name: str,
                    n_microbatches: int):
    """shard_map body. `stage_params` leaves carry this stage's leading-axis
    slice (layers-per-stage first dim); `x_mb` is (M, mb, ...) replicated
    over the pipe axis."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ray_tpu._private.jax_compat import axis_size

    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    ticks = n_microbatches + n_stages - 1
    # Shift chain toward the next stage; the final stage's output is dropped
    # from the permute ring (open chain, not a ring — no wraparound hazard).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    state = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
    out_buf = jnp.zeros_like(x_mb)
    if hasattr(lax, "pcast"):
        # Carry values mix in ppermuted data, so they are device-varying over
        # `pipe`; mark the zero inits to satisfy shard_map's vma check.
        state = lax.pcast(state, (axis_name,), to="varying")
        out_buf = lax.pcast(out_buf, (axis_name,), to="varying")

    def tick(carry, t):
        state, out_buf = carry
        mb = jnp.clip(t, 0, n_microbatches - 1)
        inp = jnp.where(stage == 0, x_mb[mb], state)
        out = stage_fn(stage_params, inp)
        oi = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
        write = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        out_buf = jnp.where(
            write, lax.dynamic_update_index_in_dim(out_buf, out, oi, 0), out_buf)
        state = lax.ppermute(out, axis_name, perm)
        return (state, out_buf), None

    (state, out_buf), _ = lax.scan(tick, (state, out_buf), jnp.arange(ticks))
    # Only the last stage holds real outputs; psum over the open chain
    # replicates them to every stage (zeros elsewhere; the sum is exact in
    # any dtype since exactly one term is nonzero).  On CPU the carry is
    # already fp32 (see pipeline_apply's carry_fp32 workaround).
    out_buf = jnp.where(stage == n_stages - 1, out_buf, jnp.zeros_like(out_buf))
    return lax.psum(out_buf, axis_name)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any],
                   stage_params: Any,
                   x,
                   *,
                   n_microbatches: int,
                   axis_name: str = PIPE_AXIS,
                   mesh=None):
    """Run `x` through a pipeline of identical stages over the `pipe` axis.

    Args:
      stage_fn: (local_params, activations) -> activations.  Receives the
        LOCAL leading-axis slice of `stage_params` (shape
        (layers_per_stage, ...) per leaf) — typically it `lax.scan`s its
        layers.  Must preserve the activation shape (pipelines are
        shape-homogeneous by construction).
      stage_params: pytree whose leaves have a leading stacked-layer axis
        divisible by the pipe axis size; sharded leading-dim over `pipe`
        (logical axis name "layers", mesh.DEFAULT_RULES).
      x: (B, ...) activations; B % n_microbatches == 0.
      n_microbatches: GPipe microbatch count M (bubble = (S-1)/(S+M-1)).
      mesh: optional; defaults to the ambient mesh (jax.set_mesh).

    Returns activations of x's shape, replicated over `pipe` (sharding over
    all other mesh axes is untouched — they stay auto).
    """
    import jax
    import jax.numpy as jnp

    P = jax.sharding.PartitionSpec
    B = x.shape[0]
    if B % n_microbatches != 0:
        raise ValueError(f"batch {B} % n_microbatches {n_microbatches} != 0")

    # Validate the layer stack against the ACTUAL pipe axis size (the mesh is
    # authoritative — a config's stage count can silently disagree with it).
    from ray_tpu._private.jax_compat import get_abstract_mesh
    from ray_tpu._private.jax_compat import shard_map as _shard_map

    resolved = mesh if mesh is not None else get_abstract_mesh()
    if resolved is not None and axis_name in getattr(resolved, "shape", {}):
        n_stages = resolved.shape[axis_name]
        for leaf in jax.tree.leaves(stage_params):
            if leaf.shape[0] % n_stages:
                raise ValueError(
                    f"stage_params leading dim {leaf.shape[0]} not divisible "
                    f"by pipe axis size {n_stages}")

    # XLA CPU (the 8-virtual-device test platform) miscompiles the bf16
    # psum_invariant all-reduce that AD emits for the replicated microbatch
    # input (checkfail in AllReducePromotion).  Carry activations in fp32
    # there; on TPU the carry stays in the compute dtype.
    compute_dtype = x.dtype
    carry_fp32 = (jax.default_backend() == "cpu"
                  and compute_dtype == jnp.bfloat16)
    if carry_fp32:
        x = x.astype(jnp.float32)
        inner_fn, stage_fn = stage_fn, lambda p, h: inner_fn(
            p, h.astype(compute_dtype)).astype(jnp.float32)
    x_mb = x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])

    params_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = partial(_pipeline_local, stage_fn, axis_name=axis_name,
                 n_microbatches=n_microbatches)
    out = _shard_map(fn, mesh=mesh,
                     in_specs=(params_spec, P()),
                     out_specs=P(),
                     axis_names={axis_name})(stage_params, x_mb)
    return out.reshape(B, *x.shape[1:]).astype(compute_dtype)
