"""Per-worker train session: the report() channel and worker context.

(ref: python/ray/train/_internal/session.py — _TrainSession:112, report
:405/:672: a queue between the user's training thread and the controller).
Here the worker IS a thread in the controller's process, so the session is a
thread-local object with a plain queue the controller drains.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train import profiler as _profiler
from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


class TrainContext:
    """What the user's train_loop sees via get_context()
    (ref: train/context.py TrainContext)."""

    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 node_rank: int = 0, trial_name: str = "",
                 experiment_name: str = "", group_name: str = "train"):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.trial_name = trial_name
        self.experiment_name = experiment_name
        self.collective_group = group_name

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_experiment_name(self) -> str:
        return self.experiment_name


class TrainSession:
    def __init__(self, context: TrainContext,
                 checkpoint_to_restore: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 shard_writer=None, start_step: int = 0,
                 dataset_config=None, profiler=None):
        self.context = context
        self.results: "queue.Queue" = queue.Queue()
        self.checkpoint_to_restore = checkpoint_to_restore
        self.dataset_shards = dataset_shards or {}
        #: the Trainer's DatasetConfig — user loops read it through
        #: train.get_dataset_config() for prefetch/shuffle tuning knobs.
        self.dataset_config = dataset_config
        self.stop_requested = threading.Event()
        #: ray_tpu.checkpoint.ShardWriter when async checkpointing is on
        #: (CheckpointConfig.async_save) — report(checkpoint=<pytree>) then
        #: goes through the coordinator's two-phase commit instead of the
        #: in-band queue, blocking only for the device->host snapshot.
        self.shard_writer = shard_writer
        #: next coordinator step id; starts past the latest committed step
        #: so a resumed attempt never collides with history.
        self._ckpt_step = start_step
        #: how many async saves this session handed to the shard writer,
        #: and the newest SaveHandle — the trainer checks these after the
        #: run so an every-save-failed run cannot finish silently with no
        #: checkpoint and no error.
        self.async_saves_reported = 0
        self.last_save_handle = None
        #: ray_tpu.train.profiler.StepProfiler when step profiling is on
        #: (RunConfig.profile, the default) — activated on the worker
        #: thread with the session itself; report() is its step boundary.
        self.profiler = profiler

    def current_checkpoint_step(self) -> int:
        """The checkpoint step the NEXT report() will save as — the step
        currently being trained.  The elastic sample ledger tags claims
        with it so a restore knows exactly which claims rolled back."""
        return self._ckpt_step

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Any] = None) -> None:
        # Chaos: the per-step worker-crash point (also consulted at run()
        # entry by TrainWorker) — an InjectedFailure here is a worker
        # dying mid-training, which the elastic controller must survive.
        from ray_tpu._private import fault_injection

        fault_injection.check("train_worker_run")
        if checkpoint is not None and not isinstance(checkpoint, Checkpoint):
            # A raw pytree: async sharded save when wired, else wrap it in
            # a directory checkpoint so the legacy path still works.
            if self.shard_writer is not None:
                step = self._ckpt_step
                self._ckpt_step += 1
                self.last_save_handle = self.shard_writer.save_async(
                    step, checkpoint)
                self.async_saves_reported += 1
                checkpoint = None
            else:
                checkpoint = Checkpoint.from_pytree(checkpoint)
        self.results.put({"metrics": metrics, "checkpoint": checkpoint,
                          "rank": self.context.world_rank})
        # report() IS the step boundary: close the profiled step (spans +
        # live gauges) now that its checkpoint-block time is recorded.
        if self.profiler is not None:
            self.profiler.step_boundary()
        if self.stop_requested.is_set():
            raise StopIteration("Training stopped by the controller")


def init_session(session: TrainSession) -> None:
    _local.session = session
    _profiler.activate(getattr(session, "profiler", None))


def clear_session() -> None:
    _local.session = None
    _profiler.activate(None)


def get_session() -> Optional[TrainSession]:
    return getattr(_local, "session", None)


def _require_session() -> TrainSession:
    s = get_session()
    if s is None:
        raise RuntimeError(
            "No train session active — this API must be called inside a "
            "train_loop launched by a Trainer.")
    return s


# ------------------------- public functional API (ref: ray.train.*) ---------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    """(ref: session.py report:672)"""
    _require_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return _require_session().context


def get_checkpoint() -> Optional[Checkpoint]:
    """Checkpoint to resume from after a restart (ref: train.get_checkpoint)."""
    return _require_session().checkpoint_to_restore


def get_dataset_shard(name: str = "train"):
    """(ref: train.get_dataset_shard) — the worker's split of a Dataset."""
    return _require_session().dataset_shards.get(name)


def get_dataset_config():
    """The Trainer's :class:`~ray_tpu.train.DatasetConfig` (or None when
    the run was launched without one)."""
    return _require_session().dataset_config
