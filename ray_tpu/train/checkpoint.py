"""Checkpoints: directory handles + orbax-backed pytree persistence.

(ref: python/ray/train/_checkpoint.py:56 Checkpoint — a directory handle
uploaded via pyarrow fs; python/ray/train/_internal/checkpoint_manager.py —
top-K retention).  The TPU-native twist: first-class JAX pytree save/restore
via orbax, the standard JAX checkpoint library, so sharded arrays round-trip
without host gathers when meshes match.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class Checkpoint:
    """A handle to a checkpoint directory (ref: _checkpoint.py:56)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_pytree(cls, tree: Any, path: Optional[str] = None) -> "Checkpoint":
        """Persist a JAX pytree with orbax."""
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        save_pytree(tree, os.path.join(path, "pytree"))
        return cls(path)

    def to_pytree(self, template: Optional[Any] = None) -> Any:
        # A committed sharded checkpoint (ray_tpu.checkpoint two-phase
        # commit layout, COMMIT marker present) restores through the
        # subsystem; the orbax single-dir layout stays the default — one
        # handle type works for both, which is what lets Trainer
        # auto-resume hand either kind to train.get_checkpoint().
        from ray_tpu.checkpoint import is_committed_dir, restore_pytree

        if is_committed_dir(self.path):
            return restore_pytree(self.path, template)
        return load_pytree(os.path.join(self.path, "pytree"), template)

    def as_directory(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield self.path

        return ctx()

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, "metadata.json"), "w") as f:
            json.dump(metadata, f)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, "metadata.json")
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def __repr__(self) -> str:
        return f"Checkpoint({self.path})"


def _orbax_save(tree: Any, path: str) -> None:
    """The raw orbax write (factored out so tests can fail it mid-save)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)


def save_pytree(tree: Any, path: str) -> None:
    """Atomic pytree save: write a ``*.tmp`` sibling, then rename into
    place.  The previous rmtree-then-save ordering meant a crash mid-save
    destroyed the PREVIOUS checkpoint too; now the old directory survives
    until the new one is fully on disk."""
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    if os.path.exists(tmp):  # stale leftover from a crashed save
        shutil.rmtree(tmp)
    _orbax_save(tree, tmp)
    if os.path.exists(path):
        # os.replace cannot clobber a non-empty dir: swap via a sibling so
        # there is never a moment with no complete checkpoint on disk.
        old = path + ".old"
        shutil.rmtree(old, ignore_errors=True)
        os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmp, path)


def load_pytree(path: str, template: Optional[Any] = None) -> Any:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if template is not None:
            return ckptr.restore(path, template)
        return ckptr.restore(path)


def pack_checkpoint(checkpoint: Optional[Checkpoint]) -> Optional[bytes]:
    """Checkpoint directory -> tar.gz bytes, for shipping across hosts.

    Multi-host trainer workers live on other machines: a path-valued
    Checkpoint is meaningless there, so report/restore moves the directory
    by value through the object plane (ref: the reference syncs checkpoint
    dirs through storage_path/pyarrow fs — train/_internal/storage.py; an
    in-band copy is the storage-less equivalent)."""
    if checkpoint is None:
        return None
    import io
    import tarfile

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        tar.add(checkpoint.path, arcname=".")
    return buf.getvalue()


def unpack_checkpoint(blob: Optional[bytes],
                      path: Optional[str] = None) -> Optional[Checkpoint]:
    """Inverse of pack_checkpoint: extract into a fresh local directory."""
    if blob is None:
        return None
    import io
    import tarfile

    path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
    with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
        tar.extractall(path, filter="data")
    return Checkpoint(path)


class CheckpointManager:
    """Top-K checkpoint retention (ref: _internal/checkpoint_manager.py)."""

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._checkpoints: List[Tuple[float, Checkpoint, Dict]] = []
        self._counter = 0
        self._lock = threading.Lock()
        os.makedirs(storage_path, exist_ok=True)
        # Restart-safe: rebuild the registry from what is already on disk,
        # so latest_checkpoint()/best_checkpoint() survive a driver restart
        # instead of returning None while the directories sit right there.
        self._rescan()

    def _rescan(self) -> None:
        from ray_tpu.checkpoint.layout import COMMIT_MARKER, parse_step

        for name in sorted(os.listdir(self.storage_path)):
            path = os.path.join(self.storage_path, name)
            if not os.path.isdir(path) or name.endswith(".tmp") \
                    or name.endswith(".old"):
                continue
            if not name.startswith("checkpoint_"):
                continue
            has_shards = any(e.startswith("shard_") for e in os.listdir(path))
            if has_shards and not os.path.exists(os.path.join(path, COMMIT_MARKER)):
                continue  # torn sharded save — never register it
            ckpt = Checkpoint(path)
            meta = ckpt.get_metadata()
            idx = meta.get("index")
            if idx is None:
                idx = parse_step(name)
            if idx is None:
                continue
            metrics = meta.get("metrics", {})
            if self.score_attribute and self.score_attribute in metrics:
                score = float(metrics[self.score_attribute])
            else:
                score = float(idx)
            self._checkpoints.append((score, ckpt, metrics))
            self._counter = max(self._counter, int(idx))

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Move/copy the checkpoint into managed storage and apply retention."""
        from ray_tpu.checkpoint.layout import COMMIT_MARKER

        with self._lock:
            while True:
                self._counter += 1
                dest = os.path.join(self.storage_path,
                                    f"checkpoint_{self._counter:06d}")
                # Never clobber a coordinator-committed sharded step that
                # landed after our rescan (the two sides number dirs from
                # independent counters): seed the counter past it instead.
                if not os.path.exists(os.path.join(dest, COMMIT_MARKER)):
                    break
            if os.path.abspath(checkpoint.path) != dest:
                if os.path.exists(dest):
                    shutil.rmtree(dest)
                src = checkpoint.path
                is_temp = os.path.basename(src).startswith("ray_tpu_ckpt_") and \
                    src.startswith(tempfile.gettempdir())
                if is_temp:
                    # from_pytree tempdirs are single-use: move, don't leak a
                    # model-sized copy in /tmp per report.
                    shutil.move(src, dest)
                else:
                    shutil.copytree(src, dest)
            managed = Checkpoint(dest)
            managed.update_metadata({"metrics": _json_safe(metrics), "index": self._counter,
                                     "time": time.time()})
            if self.score_attribute and self.score_attribute in metrics:
                score = float(metrics[self.score_attribute])
            else:
                score = float(self._counter)  # recency
            self._checkpoints.append((score, managed, metrics))
            self._apply_retention()
            return managed

    def _apply_retention(self) -> None:
        if self.num_to_keep is None or len(self._checkpoints) <= self.num_to_keep:
            return
        from ray_tpu.checkpoint.layout import COMMIT_MARKER

        reverse = self.score_order == "max"
        self._checkpoints.sort(key=lambda t: t[0], reverse=reverse)
        for _, ckpt, _ in self._checkpoints[self.num_to_keep:]:
            # Coordinator-committed sharded dirs are the coordinator's to
            # retire (its own keep= policy): evict from this registry but
            # leave the directory alone.
            if os.path.exists(os.path.join(ckpt.path, COMMIT_MARKER)):
                continue
            shutil.rmtree(ckpt.path, ignore_errors=True)
        self._checkpoints = self._checkpoints[: self.num_to_keep]

    def best_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            reverse = self.score_order == "max"
            return sorted(self._checkpoints, key=lambda t: t[0], reverse=reverse)[0][1]

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            return max(self._checkpoints, key=lambda t: _ckpt_index(t[1]))[1]


def _ckpt_index(ckpt: Checkpoint) -> int:
    """Recency index: metadata wins, else the checkpoint_NNNNNN name
    (coordinator-committed dirs carry no metadata.json)."""
    idx = ckpt.get_metadata().get("index")
    if idx is not None:
        return int(idx)
    from ray_tpu.checkpoint.layout import parse_step

    return parse_step(os.path.basename(os.path.normpath(ckpt.path))) or 0


def _json_safe(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = repr(v)
    return out
