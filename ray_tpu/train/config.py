"""Train configuration dataclasses
(ref: python/ray/air/config.py — ScalingConfig, RunConfig, FailureConfig,
CheckpointConfig; python/ray/train/ re-exports them)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    """(ref: air/config.py ScalingConfig)

    num_workers: size of the worker group.  use_tpu pins each worker to
    chips; on a single host the workers are threads sharing the JAX client
    and the collective group maps them onto the device mesh.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    #: chips per worker when use_tpu (the reference's GPUs-per-worker analogue)
    tpus_per_worker: float = 1.0
    placement_strategy: str = "PACK"
    #: "threads" — workers share this process's JAX client (single TPU host);
    #: "processes" — each worker is its own OS process joined into one
    #: jax.distributed cluster (multi-host SPMD, ref: backend_executor.py:69
    #: worker actors across nodes); "auto" — processes iff the placement
    #: group's bundles land on worker nodes beyond the head.
    worker_mode: str = "auto"
    #: Dynamic world size (preemption-tolerant training); None = a lost
    #: worker restarts the attempt at the SAME world size (legacy).
    elastic: Optional["ElasticConfig"] = None

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        res: Dict[str, float] = {"CPU": 1.0}
        if self.use_tpu:
            res = {"TPU": self.tpus_per_worker}
        return res


@dataclass
class ElasticConfig:
    """Preemption-tolerant dynamic world size (ROADMAP item 3 — the
    training-side twin of serve self-healing).

    With ``ScalingConfig(elastic=ElasticConfig(...))`` the Trainer treats
    ``num_workers`` as a *target*, not a contract: on worker/node loss it
    shrinks the collective group and mesh to surviving capacity (never
    below ``min_workers``), elastic-restores the last committed step —
    preferring the in-memory replica tier — reshards the sample ledger so
    every not-yet-trained sample lands on exactly one surviving worker,
    and resumes inside the same ``fit()`` call.  Capacity is re-checked
    every ``grow_check_period_s``; when it supports more workers again the
    group grows back at the next checkpoint boundary (never above
    ``max_workers``, which defaults to ``num_workers``).
    """

    min_workers: int = 1
    max_workers: Optional[int] = None
    grow_check_period_s: float = 2.0

    def resolve_max(self, num_workers: int) -> int:
        return self.max_workers if self.max_workers is not None else num_workers


@dataclass
class DatasetConfig:
    """How the Trainer feeds ``datasets=`` to workers
    (docs/data-ingestion.md).

    With ``streaming=True`` (the default) every lazy Dataset becomes a
    :class:`~ray_tpu.data.ingest.StreamingIngest`: workers claim source
    shards through a per-epoch ledger and stream them through a
    backpressured executor, a windowed shuffle (O(window) memory, never a
    full-epoch materialization), rebatching and host prefetch —
    ``get_dataset_shard()`` then returns an ``IngestShard``.  With
    ``streaming=False`` the legacy path applies: ``streaming_split`` into
    per-worker ``DataIterator``s (row-balanced, but the whole epoch's
    blocks flow through a central coordinator).
    """

    streaming: bool = True
    #: Shuffle window, in blocks, per worker.  1 disables shuffling beyond
    #: the epoch's shard-order permutation.
    shuffle_window_blocks: int = 16
    #: Epoch shuffles derive from (seed, epoch); None = fresh per process.
    shuffle_seed: Optional[int] = None
    #: Host-side prefetch depth, in batches.  0 disables the pump thread.
    prefetch_batches: int = 2
    #: In-flight byte budget per worker: fetch-ahead + shuffle window.
    window_bytes: int = 128 << 20
    #: Reserved for device double-buffering via
    #: ``IngestShard.iter_batches(device_sharding=...)``.
    device_prefetch: bool = False


@dataclass
class FailureConfig:
    """(ref: air/config.py FailureConfig) max_failures=-1 retries forever."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """(ref: air/config.py CheckpointConfig) top-K retention.

    async_save routes ``train.report(..., checkpoint=<pytree>)`` through
    the ray_tpu.checkpoint subsystem: the step blocks only for the
    device->host snapshot, shards persist in background threads and a
    CheckpointCoordinator two-phase-commits each step (see
    docs/checkpointing.md).  replica_memory_steps controls how many
    committed steps the in-memory replica tier keeps for fast recovery.
    """

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    async_save: bool = False
    replica_memory_steps: int = 2


@dataclass
class RunConfig:
    """(ref: air/config.py RunConfig)"""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    #: trial stop criteria, e.g. {"training_iteration": 10} (ref: air
    #: RunConfig.stop)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 0
    #: Tune/experiment callbacks — logger integrations live here (ref: air
    #: RunConfig.callbacks; `ray_tpu.air.integrations` wandb/mlflow/TBX).
    callbacks: Optional[list] = None
    #: Step-time attribution (docs/observability.md): every worker gets a
    #: StepProfiler that splits each step's wall time into data-wait /
    #: h2d / compute / collective-sync / checkpoint-block buckets, exports
    #: the ray_tpu_train_* gauges (MFU, tokens/s, step percentiles) and —
    #: when tracing is on — emits train.* spans into the timeline.  Costs
    #: a few timestamps per step; set False to strip even that.
    profile: bool = True
