"""Elastic data plane: the exactly-once sample ledger.

The reshard guarantee (docs/elastic-training.md): across any sequence of
preemptions, shrinks and grows inside one ``fit()``, every sample is
trained by exactly one worker exactly once — none double-trained, none
dropped — where "trained" means *its gradient contributed to the state
the run finished with*.

Mechanism: workers do not own static shards.  A single controller-side
``SampleLedger`` (thread-tier workers share the controller's process)
hands out exclusive batches; a claim is *provisional*, tagged with the
checkpoint step the worker is about to train, until a checkpoint at or
past that step commits — then it is sealed (permanently trained).  On a
preemption the model rolls back to the last committed step S, so every
provisional claim past S describes an update the restored model never
saw: those samples are requeued (front of the queue, original order) and
handed to a surviving worker.  Claims at or below S sealed with the
restore.  Shrink/grow need no repartitioning step at all — exclusive
claiming IS the reshard.

Without an async-checkpoint coordinator there is no committed-step
signal; ``seal_on_claim=True`` degrades to claim-is-trained (a failure
loses those samples' contribution instead of retraining them — still
never double-trained).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.train import profiler as _profiler

#: Claim tag for work whose final training step is not known at claim time
#: (streaming ingest claims a whole source shard up front and only learns
#: the step its last batch trained at once the shard drains).  Larger than
#: any real checkpoint step, so seal(committed) never seals it by accident;
#: ``retag()`` replaces it once the true step is known, and ``rollback()``
#: requeues anything still provisional, exactly like a normal claim.
PROVISIONAL_STEP = 1 << 62


class SampleLedger:
    """Controller-owned exactly-once dispenser over a sized dataset."""

    def __init__(self, dataset: Sequence, seal_on_claim: bool = False):
        self._dataset = dataset
        self._lock = threading.Lock()
        self._pending: deque = deque(range(len(dataset)))  # guarded_by: _lock
        #: provisional claims in claim order: (step, (idx, ...))
        self._inflight: List[Tuple[int, Tuple[int, ...]]] = []  # guarded_by: _lock
        #: idx -> times sealed (>1 would mean a double-train)
        self._trained: Dict[int, int] = {}  # guarded_by: _lock
        self.seal_on_claim = seal_on_claim

    def __len__(self) -> int:
        return len(self._dataset)

    # ------------------------------------------------------------- claims
    def claim(self, n: int, step: Optional[int] = None,
              fence=None, prefer=None) -> Optional[Tuple[int, ...]]:
        """Exclusively claim up to ``n`` sample indices for checkpoint
        step ``step``; None once the queue is empty.

        ``fence`` (a threading.Event, the session's stop_requested): a
        zombie worker thread — its actor killed by a preemption but its
        Python thread still running — must not claim after the controller
        rolls the ledger back, or the claim's samples would be counted
        trained in a discarded lineage.  The fence is checked under the
        ledger lock and the controller always sets it BEFORE rolling
        back, so every interleaving either rejects the claim or lands it
        in _inflight where the rollback requeues it.

        ``prefer`` (``idx -> bool``): soft locality preference — indices
        the predicate accepts are claimed first (in queue order), the
        rest fill from the queue head as usual.  Purely an ordering hint:
        exactly-once accounting, rollback and exhaustion are unchanged,
        and no index is ever skipped (the streaming-ingest locality path,
        docs/cluster-autoscaling.md)."""
        with self._lock:
            if fence is not None and fence.is_set():
                return None
            if not self._pending:
                return None
            take = min(n, len(self._pending))
            if prefer is not None:
                chosen: List[int] = []
                for i in self._pending:
                    if len(chosen) >= take:
                        break
                    if prefer(i):
                        chosen.append(i)
                for i in chosen:
                    self._pending.remove(i)
                while len(chosen) < take:
                    chosen.append(self._pending.popleft())
                indices = tuple(chosen)
            else:
                indices = tuple(self._pending.popleft()
                                for _ in range(take))
            if self.seal_on_claim or step is None:
                for i in indices:
                    self._trained[i] = self._trained.get(i, 0) + 1
            else:
                self._inflight.append((step, indices))
            return indices

    def fetch(self, indices: Tuple[int, ...]):
        """Materialize claimed samples (numpy fancy-indexing when the
        dataset supports it, else item-by-item)."""
        try:
            return self._dataset[list(indices)]
        except TypeError:
            return [self._dataset[i] for i in indices]

    def retag(self, indices: Tuple[int, ...], step: Optional[int]) -> int:
        """Replace the claim step of in-flight ``indices`` (claimed at
        ``PROVISIONAL_STEP``) with the step they actually finished training
        at — the streaming-ingest path, where a shard's step is only known
        once its last batch has been consumed.  ``step=None`` seals the
        indices immediately (no coordinator to commit against).  Indices no
        longer in flight (already requeued by a rollback) are skipped;
        returns how many were retagged/sealed."""
        want = set(indices)
        with self._lock:
            moved = 0
            keep: List[Tuple[int, Tuple[int, ...]]] = []
            for s, idxs in self._inflight:
                hit = [i for i in idxs if i in want]
                if not hit:
                    keep.append((s, idxs))
                    continue
                moved += len(hit)
                rest = tuple(i for i in idxs if i not in want)
                if rest:
                    keep.append((s, rest))
                if step is None:
                    for i in hit:
                        self._trained[i] = self._trained.get(i, 0) + 1
                else:
                    keep.append((step, tuple(hit)))
            self._inflight = keep
            return moved

    # ------------------------------------------------- commit/rollback
    def seal(self, committed_step: int) -> int:
        """A checkpoint at ``committed_step`` committed: claims trained at
        or before it are now permanent.  Returns how many were sealed."""
        with self._lock:
            return self._seal_locked(committed_step)

    def _seal_locked(self, committed_step: int) -> int:
        sealed = 0
        keep: List[Tuple[int, Tuple[int, ...]]] = []
        for step, indices in self._inflight:
            if step <= committed_step:
                for i in indices:
                    self._trained[i] = self._trained.get(i, 0) + 1
                sealed += len(indices)
            else:
                keep.append((step, indices))
        self._inflight = keep
        return sealed

    def seal_all(self) -> int:
        """Clean finish: nothing will roll back, every provisional claim
        is trained."""
        with self._lock:
            sealed = 0
            for _, indices in self._inflight:
                for i in indices:
                    self._trained[i] = self._trained.get(i, 0) + 1
                sealed += len(indices)
            self._inflight = []
            return sealed

    def rollback(self, restore_step: Optional[int]) -> int:
        """The model restored to ``restore_step`` (None = from scratch):
        provisional claims past it describe rolled-back updates — requeue
        them, front of the queue, original claim order, so a surviving
        worker retrains each exactly once.  Claims at or below the restore
        step seal.  Returns how many samples were requeued."""
        with self._lock:
            if restore_step is not None:
                self._seal_locked(restore_step)
            requeue: List[int] = []
            for _, indices in self._inflight:
                requeue.extend(indices)
            self._inflight = []
            for i in reversed(requeue):
                self._pending.appendleft(i)
            return len(requeue)

    # --------------------------------------------------------- inspection
    def remaining(self) -> int:
        with self._lock:
            return len(self._pending)

    def inflight(self) -> int:
        with self._lock:
            return sum(len(ix) for _, ix in self._inflight)

    def exhausted(self) -> bool:
        """No work left to hand out AND nothing provisional that a
        rollback could still requeue."""
        with self._lock:
            return not self._pending and not self._inflight

    def trained_counts(self) -> Dict[int, int]:
        """idx -> times permanently trained (the per-sample ledger the
        chaos acceptance test asserts on)."""
        with self._lock:
            return dict(self._trained)

    def double_trained(self) -> List[int]:
        return [i for i, c in self.trained_counts().items() if c > 1]

    def untrained(self) -> List[int]:
        counts = self.trained_counts()
        return [i for i in range(len(self._dataset)) if counts.get(i, 0) == 0]


class ElasticDatasetShard:
    """A worker's view of the shared ledger, handed out by
    ``train.get_dataset_shard()`` when elastic training is on.

    Batches are claimed tagged with the session's NEXT checkpoint step —
    the step whose ``report()`` has not happened yet — so the ledger can
    tell exactly which claims a restore to step S rolls back.
    """

    def __init__(self, ledger: SampleLedger, session=None):
        self._ledger = ledger
        self._session = session

    def next_batch(self, batch_size: int):
        """(indices, samples) for an exclusively claimed batch, or None
        when every sample has been handed out (or this attempt is being
        torn down — see the fence note on SampleLedger.claim)."""
        step = None
        fence = None
        if self._session is not None:
            step = self._session.current_checkpoint_step()
            fence = self._session.stop_requested
        # Claim + fetch is the worker's input-pipeline time on the elastic
        # (non-streaming) path — the step profiler's data_wait bucket.
        w0 = time.time()
        try:
            indices = self._ledger.claim(batch_size, step, fence=fence)
            if indices is None:
                return None
            return indices, self._ledger.fetch(indices)
        finally:
            _profiler.record("data_wait", w0, time.time())

    def iter_batches(self, batch_size: int):
        while True:
            batch = self.next_batch(batch_size)
            if batch is None:
                return
            yield batch

    def __len__(self) -> int:
        return len(self._ledger)
