"""Per-step train profiler: wall-time attribution, live MFU, step spans.

Answers the ROADMAP item-4 question ("where does the non-compute time
go?") continuously instead of via one-shot probe scripts: every training
step's wall clock — one ``report()`` to the next — is attributed into

* ``data_wait``   — blocked on the input pipeline (prefetch starvation,
  elastic ledger claim + fetch);
* ``h2d``         — host→device transfer dispatch
  (:class:`~ray_tpu.data.ingest.prefetch.DeviceBatchIterator`);
* ``collective``  — gradient-sync rendezvous (entering a collective to
  getting its result back);
* ``ckpt_block``  — the device→host snapshot an async checkpoint save
  blocks the step for (:meth:`ShardWriter.save_async`);
* ``compute``     — the residual.  Defining compute as ``wall − Σ other``
  makes the buckets sum to the measured wall time *by construction* —
  un-instrumented host work lands in compute rather than vanishing.

The profiler is **per worker thread** (thread-local, like the session it
belongs to), so ``record()`` needs no lock: every hook site — prefetcher
consumption, device transfer, collective contribute, snapshot — runs on
the worker's own thread.  Hook modules outside ``train/`` reach it
through a ``sys.modules`` probe (see :func:`record`'s callers), so they
never import the train package and pay one dict lookup when training is
not in the process at all.

Step closure (``step_boundary``, called from ``TrainSession.report``)
emits the PR 4 span machinery retroactively — a ``train.step`` parent
span with one child span per recorded interval — and refreshes the
``ray_tpu_train_*`` gauges (MFU, tokens/s, step-time p50/p95, data-
starved fraction).  Spans cost nothing when tracing is off; the whole
profiler is skipped when ``RunConfig(profile=False)``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train import metrics as train_metrics
from ray_tpu.util import tracing, watchdog

#: Attribution buckets measured by hooks; ``compute`` is the residual.
BUCKETS = ("data_wait", "h2d", "collective", "ckpt_block")

#: Per-bucket cap on *span* intervals kept per step — totals always
#: accumulate, but a step with thousands of tiny waits must not emit
#: thousands of spans.
_MAX_INTERVALS = 64

#: Recent step walls for the live p50/p95 gauges (sliding, not lifetime —
#: a regression shows up within a window, not diluted by history).
_PCTL_WINDOW = 128

_local = threading.local()


class StepProfiler:
    """Wall-time attribution for one worker's training steps.

    Lives on the worker's :class:`~ray_tpu.train.session.TrainSession`;
    activated/deactivated with the session itself (``init_session`` /
    ``clear_session``).  All methods are called from the worker thread.
    """

    def __init__(self, run_name: str = "", rank: int = 0,
                 flops_per_step: Optional[float] = None,
                 tokens_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 history_steps: int = 512):
        self.run_name = run_name
        self.rank = rank
        self.flops_per_step = flops_per_step
        self.tokens_per_step = tokens_per_step
        self.peak_flops = peak_flops
        #: per-step attribution rows (bounded) — the bench and the state
        #: API read these; each row's buckets sum to its wall.
        self.history: "deque" = deque(maxlen=history_steps)
        # Lock-free by thread-local discipline: the profiler is reached
        # through ``_local`` so every hook site runs on the worker's own
        # thread — the ownership labels document (and let the analyzer
        # police) that no spawned thread may touch the step state.
        self._step = 0  # owned_by_thread: worker thread (thread-local _local)
        self._step_start: Optional[float] = None  # owned_by_thread: worker thread (thread-local _local)
        self._totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}  # owned_by_thread: worker thread (thread-local _local)
        self._intervals: Dict[str, List[Tuple[float, float]]] = {  # owned_by_thread: worker thread (thread-local _local)
            b: [] for b in BUCKETS}
        self._recent_walls: "deque" = deque(maxlen=_PCTL_WINDOW)  # owned_by_thread: worker thread (thread-local _local)

    # ------------------------------------------------------------- config
    def configure(self, *, flops_per_step: Optional[float] = None,
                  tokens_per_step: Optional[float] = None,
                  peak_flops: Optional[float] = None) -> None:
        """Set the MFU/throughput inputs (typically once, from inside the
        train loop, after the model is built)."""
        if flops_per_step is not None:
            self.flops_per_step = float(flops_per_step)
        if tokens_per_step is not None:
            self.tokens_per_step = float(tokens_per_step)
        if peak_flops is not None:
            self.peak_flops = float(peak_flops)

    # -------------------------------------------------------------- hooks
    def start(self, now: Optional[float] = None) -> None:
        """Open the first step window (activation time)."""
        if self._step_start is None:
            self._step_start = time.time() if now is None else now

    def record(self, bucket: str, start: float, end: float) -> None:
        """Attribute [start, end] (``time.time()`` seconds) to a bucket.

        Called from the hook sites on the worker thread; must stay cheap
        — two dict lookups, an add and (usually) an append."""
        dur = end - start
        if dur <= 0.0:
            return
        self._totals[bucket] += dur
        iv = self._intervals[bucket]
        if len(iv) < _MAX_INTERVALS:
            iv.append((start, end))
        if self._step_start is None:
            self._step_start = start

    # ----------------------------------------------------------- boundary
    def step_boundary(self, now: Optional[float] = None) -> Optional[dict]:
        """Close the current step: attribute its wall, emit spans, refresh
        the live gauges.  Returns the attribution row (or None before the
        first window opened)."""
        t1 = time.time() if now is None else now
        t0 = self._step_start
        if t0 is None or t1 <= t0:
            self._reset(t1)
            return None
        wall = t1 - t0
        totals = {b: min(self._totals[b], wall) for b in BUCKETS}
        compute = max(0.0, wall - sum(totals.values()))
        row = {"step": self._step, "wall": wall, "compute": compute,
               **totals}
        self.history.append(row)
        # Progress heartbeat: step closure feeds the hang watchdog (stall
        # = beats stop) and the straggler check (cross-worker dispersion
        # of these walls).
        watchdog.beat(f"train:{self.run_name}:{self.rank}", wall=wall)
        self._emit_spans(t0, t1, compute, row)
        self._update_metrics(wall, totals, row)
        self._step += 1
        self._reset(t1)
        return row

    def _reset(self, t1: float) -> None:
        self._step_start = t1
        for b in BUCKETS:
            self._totals[b] = 0.0
            self._intervals[b].clear()

    # -------------------------------------------------------------- spans
    def _emit_spans(self, t0: float, t1: float, compute: float,
                    row: dict) -> None:
        if not tracing.is_tracing_enabled():
            return
        parent = tracing.record_span(
            "train.step", t0, t1,
            attributes={"step": row["step"], "rank": self.rank,
                        "run": self.run_name,
                        "compute_s": round(compute, 6)})
        if parent is None:
            return
        iv = self._intervals
        tracing.record_span_batch(
            "train.data_wait", [(s, e, parent) for s, e in iv["data_wait"]])
        tracing.record_span_batch(
            "train.h2d", [(s, e, parent) for s, e in iv["h2d"]])
        tracing.record_span_batch(
            "train.collective",
            [(s, e, parent) for s, e in iv["collective"]])
        tracing.record_span_batch(
            "train.ckpt_block",
            [(s, e, parent) for s, e in iv["ckpt_block"]])
        if compute > 0.0:
            # The residual has no measured interval; render it anchored at
            # the step start so the lane shows its share of the step.
            tracing.record_span("train.compute", t0, t0 + compute,
                                parent=parent,
                                attributes={"residual": True})

    # ------------------------------------------------------------- gauges
    def _update_metrics(self, wall: float, totals: Dict[str, float],
                        row: dict) -> None:
        m = train_metrics
        m.STEPS_PROFILED.inc()
        m.STEP_SECONDS.observe(wall)
        self._recent_walls.append(wall)
        walls = sorted(self._recent_walls)
        m.STEP_P50_SECONDS.set(walls[len(walls) // 2])
        m.STEP_P95_SECONDS.set(walls[min(len(walls) - 1,
                                         int(len(walls) * 0.95))])
        m.DATA_STARVED_FRACTION.set(totals["data_wait"] / wall)
        for bucket, dur in totals.items():
            m.STEP_BUCKET_SECONDS.set(dur, {"bucket": bucket})
        m.STEP_BUCKET_SECONDS.set(row["compute"], {"bucket": "compute"})
        if self.tokens_per_step:
            m.TOKENS_PER_SECOND.set(self.tokens_per_step / wall)
        if self.flops_per_step and self.peak_flops:
            m.MFU.set(self.flops_per_step / wall / self.peak_flops)

    # ------------------------------------------------------------ queries
    def last_attribution(self) -> Optional[dict]:
        return self.history[-1] if self.history else None


# ---------------------------------------------------------------- thread API
def activate(profiler: Optional[StepProfiler]) -> None:
    """Bind a profiler to the calling thread (the session lifecycle calls
    this; ``None`` unbinds)."""
    _local.profiler = profiler
    if profiler is not None:
        profiler.start()


def active_profiler() -> Optional[StepProfiler]:
    return getattr(_local, "profiler", None)


def record(bucket: str, start: float, end: float) -> None:
    """Hook entry point: attribute an interval to the calling thread's
    profiler; no-op when the thread isn't a profiled train worker.

    Modules outside ``train/`` must not import this package for it (the
    train package import pulls the trainer → collective chain); they probe
    ``sys.modules.get("ray_tpu.train.profiler")`` instead — if the module
    was never imported, no profiler can be active anywhere.
    """
    p = getattr(_local, "profiler", None)
    if p is not None:
        p.record(bucket, start, end)


def configure(**kwargs: Any) -> None:
    """Set MFU/throughput inputs on the calling worker's profiler (no-op
    outside a profiled train loop) — see :meth:`StepProfiler.configure`."""
    p = getattr(_local, "profiler", None)
    if p is not None:
        p.configure(**kwargs)
