"""Process-local registry of train runs — what ``list_train_runs()`` reads.

The training counterpart of the serve controller's deployment table: the
controller (``DataParallelTrainer.fit``) registers its run at start and
keeps the row current — world size as the elastic group shrinks/grows,
the last committed checkpoint step as the coordinator commits, elastic
events as they happen, final status — so the state API
(``ray_tpu.util.state.list_train_runs``) and the ``/api/train_runs`` REST
route return a consistent snapshot of live and finished runs without
touching the trainer's internals.

Rows live in the controller's process (thread-tier training runs there);
the registry is bounded so a long-lived driver launching many fits never
grows without limit.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

#: Finished/failed runs retained after eviction kicks in (live runs are
#: never evicted).
_MAX_FINISHED = 64
#: Elastic events kept per run row (newest last).
_MAX_EVENTS = 32

_lock = threading.Lock()
_runs: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()


def register_run(name: str, *, world_size: int, target_world: int,
                 path: str = "", elastic: bool = False) -> None:
    """Create (or reset — rerunning a name reuses it) a run row."""
    with _lock:
        _runs[name] = {
            "name": name,
            "status": "running",
            "world_size": world_size,
            "target_world": target_world,
            "elastic": elastic,
            "path": path,
            "started_at": time.time(),
            "finished_at": None,
            "last_committed_step": None,
            "last_reported_step": None,
            "attempts": 1,
            "events": [],
        }
        _runs.move_to_end(name)
        _evict_locked()


def update_run(name: str, **fields: Any) -> None:
    """Merge fields into a run row; unknown names are ignored (a row may
    have been evicted under a long-lived driver)."""
    with _lock:
        row = _runs.get(name)
        if row is None:
            return
        for k, v in fields.items():
            row[k] = v


def record_event(name: str, event: Dict[str, Any]) -> None:
    """Append an elastic shrink/grow/recover record to the run row."""
    with _lock:
        row = _runs.get(name)
        if row is None:
            return
        row["events"].append(dict(event))
        del row["events"][:-_MAX_EVENTS]


def finish_run(name: str, status: str) -> None:
    with _lock:
        row = _runs.get(name)
        if row is None:
            return
        row["status"] = status
        row["finished_at"] = time.time()
        _evict_locked()


def get_run(name: str) -> Optional[Dict[str, Any]]:
    with _lock:
        row = _runs.get(name)
        return _copy(row) if row is not None else None


def list_runs() -> List[Dict[str, Any]]:
    """Consistent snapshot of every known run (copies — callers can't
    mutate live rows)."""
    with _lock:
        return [_copy(row) for row in _runs.values()]


def clear() -> None:
    """Drop every row (tests)."""
    with _lock:
        _runs.clear()


def _copy(row: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(row)
    out["events"] = [dict(e) for e in row["events"]]
    return out


def _evict_locked() -> None:
    done = [n for n, r in _runs.items() if r["status"] != "running"]
    for name in done[:-_MAX_FINISHED] if len(done) > _MAX_FINISHED else []:
        del _runs[name]
