"""TorchTrainer — distributed data-parallel PyTorch on process-tier workers.

(ref: python/ray/train/torch/torch_trainer.py:11 TorchTrainer +
train/torch/config.py:66,115,153 _TorchBackend/_setup_torch_process_group —
each Ray Train worker actor joins a torch.distributed process group; the
user loop wraps its model with DDP via prepare_model.)

TPU-native positioning: JAX is this framework's device path — TorchTrainer
exists for CPU-side torch workloads and API parity.  Workers are
PROCESS-tier actors (torch.distributed requires one process per rank) that
rendezvous over gloo TCP; results flow back through an actor-backed report
queue (the shared-memory TrainSession of the thread tier cannot cross a
process boundary).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.session import TrainContext, init_session, clear_session
from ray_tpu.train.trainer import DataParallelTrainer
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


class ProcessTrainSession:
    """Pickle-safe session for process-tier workers: report() ships
    (metrics, checkpoint path) through an actor-backed queue instead of the
    thread tier's shared in-memory queue (ref: _TrainSession:112 — same
    contract, different transport)."""

    def __init__(self, context: TrainContext, report_queue,
                 checkpoint_to_restore: Optional[Checkpoint] = None):
        self.context = context
        self._queue = report_queue
        self.checkpoint_to_restore = checkpoint_to_restore
        self.dataset_shards: Dict[str, Any] = {}

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self._queue.put({
            "rank": self.context.world_rank,
            "metrics": dict(metrics),
            "checkpoint_path": checkpoint.path if checkpoint else None,
        })

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint_to_restore

    def get_dataset_shard(self, name: str):
        raise ValueError(
            "dataset shards are not available on process-tier torch workers "
            "(streaming iterators cannot cross the process boundary); load "
            "data inside the train_loop or use JaxTrainer")


@ray_tpu.remote
class TorchTrainWorker:
    """One torch.distributed rank in its own OS process
    (ref: _internal/worker_group.py:19 RayTrainWorker + torch backend
    on_start).  Always created with isolation='process'."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size

    def reserve_master(self) -> str:
        """Rank 0 picks the gloo rendezvous address on ITS host (ref:
        torch/config.py:66 — master address taken from the rank-0 worker's
        node, so the group can span machines)."""
        from ray_tpu.train.trainer import _reserve_addr

        return _reserve_addr()

    def setup(self, master: str) -> None:
        from datetime import timedelta

        import torch.distributed as dist

        addr, _, port = master.rpartition(":")
        os.environ["MASTER_ADDR"] = addr
        os.environ["MASTER_PORT"] = port
        # Bounded rendezvous: the probed port is TOCTOU-racy (another
        # process can steal it between probe and bind); without a timeout a
        # stolen port means every rank hangs for gloo's 30-min default while
        # fit() spins with no diagnostic.
        dist.init_process_group(
            backend="gloo",
            init_method=f"tcp://{master}",
            rank=self.rank, world_size=self.world_size,
            timeout=timedelta(seconds=60))

    def run(self, train_loop: Callable, loop_config: Optional[Dict[str, Any]],
            session: ProcessTrainSession) -> str:
        from ray_tpu.train.trainer import invoke_train_loop

        init_session(session)
        try:
            invoke_train_loop(train_loop, loop_config)
            return "done"
        finally:
            clear_session()

    def shutdown_group(self) -> None:
        import torch.distributed as dist

        if dist.is_initialized():
            dist.destroy_process_group()


def prepare_model(model):
    """Wrap the model for data-parallel training
    (ref: train/torch/train_loop_utils.py prepare_model — DDP)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


class TorchTrainer(DataParallelTrainer):
    """Same controller contract as DataParallelTrainer (elastic restarts,
    checkpoint manager, PG gang scheduling) with the worker group swapped
    for process-tier torch ranks."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.datasets:
            # Constructor-time invariant: fail before any placement-group
            # reservation is paid for a run that can never proceed.
            raise ValueError(
                "TorchTrainer does not support datasets= (process workers "
                "cannot receive streaming iterators); load data inside the "
                "train_loop or use JaxTrainer")

    def _run_with_pg(self, pg, run_name: str, group_name: str,
                     manager: CheckpointManager, restore_ckpt,
                     coordinator=None, world=None, ledgers=None,
                     ingests=None) -> Dict:
        # coordinator (async sharded checkpointing) is thread-tier only;
        # torch workers are process-tier, so it is always None here —
        # likewise the elastic world/ledgers/ingests plumbing (no
        # datasets=, and ScalingConfig.elastic is rejected for
        # process-tier groups).
        from ray_tpu.exceptions import RayTpuError, TaskError
        from ray_tpu.util.queue import Empty, Queue

        scfg = self.scaling_config
        if scfg.elastic is not None:
            return {"status": "fatal", "last_metrics": None, "history": [],
                    "error": ValueError(
                        "elastic training requires thread-tier workers; "
                        "TorchTrainer ranks are process-tier (use "
                        "JaxTrainer with ScalingConfig(worker_mode="
                        "'threads'))")}
        world = scfg.num_workers
        report_queue = Queue()
        workers = []
        sessions: List[ProcessTrainSession] = []
        for rank in range(world):
            ctx = TrainContext(world_rank=rank, world_size=world,
                               local_rank=rank, trial_name=run_name,
                               experiment_name=run_name,
                               group_name=group_name)
            sessions.append(ProcessTrainSession(ctx, report_queue,
                                                restore_ckpt))
            workers.append(
                TorchTrainWorker.options(
                    isolation="process",
                    resources=scfg.worker_resources(),
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=rank),
                ).remote(rank, world))

        try:
            master = ray_tpu.get(workers[0].reserve_master.remote(),
                                 timeout=120)
            ray_tpu.get([w.setup.remote(master) for w in workers],
                        timeout=180)
        except (TaskError, RayTpuError) as e:
            for w in workers:
                ray_tpu.kill(w)
            report_queue.shutdown()
            return {"status": "failed", "last_metrics": None, "history": [],
                    "error": e}

        refs = [w.run.remote(self.train_loop, self.train_loop_config, s)
                for w, s in zip(workers, sessions)]

        history: List[Dict[str, Any]] = []
        last_metrics: Optional[Dict[str, Any]] = None

        def drain() -> None:
            nonlocal last_metrics
            while True:
                try:
                    item = report_queue.get_nowait()
                except Empty:
                    return
                if item.get("checkpoint_path"):
                    manager.register(Checkpoint(item["checkpoint_path"]),
                                     item["metrics"])
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    history.append(item["metrics"])

        try:
            from ray_tpu.train.trainer import _drive_worker_refs

            _drive_worker_refs(refs, drain)
            for w in workers:
                try:
                    ray_tpu.get(w.shutdown_group.remote(), timeout=10)
                except Exception:
                    pass
            return {"status": "finished", "last_metrics": last_metrics,
                    "history": history, "error": None}
        except (TaskError, RayTpuError) as e:
            for w in workers:
                ray_tpu.kill(w)
            drain()
            return {"status": "failed", "last_metrics": last_metrics,
                    "history": history, "error": e}
        finally:
            try:
                report_queue.shutdown()
            except Exception:
                pass
