"""Elastic-training metrics.

Declared at import time like the serve/checkpoint metric modules so
``scripts/check_metrics.py`` lints them; exported on ``/metrics`` through
the process registry (util/metrics.py).

The anchor set is what an operator of preemption-tolerant training needs
on a dashboard: how often slices vanish, how the trainer responded
(shrink/grow), how much work each recovery cost (lost steps — bounded by
``CheckpointConfig.replica_memory_steps`` when the memory tier is on),
and how long kill→training-resumed took.
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge, Histogram

PREEMPTIONS = Counter(
    "ray_tpu_elastic_preemptions_total",
    "Worker/node preemptions observed by the elastic training layer "
    "(simulated ones from the preempt_node chaos hook included)",
)

SHRINK_EVENTS = Counter(
    "ray_tpu_elastic_shrink_events_total",
    "Times the elastic trainer shrank its world size to surviving "
    "capacity after a worker or node loss",
)

GROW_EVENTS = Counter(
    "ray_tpu_elastic_grow_events_total",
    "Times the elastic trainer grew its world size back at a checkpoint "
    "boundary after capacity returned",
)

LOST_STEPS = Counter(
    "ray_tpu_elastic_lost_steps_total",
    "Training steps rolled back across all elastic recoveries (steps "
    "reported after the last committed checkpoint at failure time)",
)

RECOVERY_SECONDS = Histogram(
    "ray_tpu_elastic_recovery_seconds",
    "Seconds from failure detection to the first report() of the resumed "
    "attempt (restore + group reform + data reshard)",
)

WORLD_SIZE = Gauge(
    "ray_tpu_elastic_world_size",
    "Current world size of the elastic training worker group",
)
