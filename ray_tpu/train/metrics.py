"""Elastic-training metrics.

Declared at import time like the serve/checkpoint metric modules so
``scripts/check_metrics.py`` lints them; exported on ``/metrics`` through
the process registry (util/metrics.py).

The anchor set is what an operator of preemption-tolerant training needs
on a dashboard: how often slices vanish, how the trainer responded
(shrink/grow), how much work each recovery cost (lost steps — bounded by
``CheckpointConfig.replica_memory_steps`` when the memory tier is on),
and how long kill→training-resumed took.
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge, Histogram

PREEMPTIONS = Counter(
    "ray_tpu_elastic_preemptions_total",
    "Worker/node preemptions observed by the elastic training layer "
    "(simulated ones from the preempt_node chaos hook included)",
)

SHRINK_EVENTS = Counter(
    "ray_tpu_elastic_shrink_events_total",
    "Times the elastic trainer shrank its world size to surviving "
    "capacity after a worker or node loss",
)

GROW_EVENTS = Counter(
    "ray_tpu_elastic_grow_events_total",
    "Times the elastic trainer grew its world size back at a checkpoint "
    "boundary after capacity returned",
)

LOST_STEPS = Counter(
    "ray_tpu_elastic_lost_steps_total",
    "Training steps rolled back across all elastic recoveries (steps "
    "reported after the last committed checkpoint at failure time)",
)

RECOVERY_SECONDS = Histogram(
    "ray_tpu_elastic_recovery_seconds",
    "Seconds from failure detection to the first report() of the resumed "
    "attempt (restore + group reform + data reshard)",
)

WORLD_SIZE = Gauge(
    "ray_tpu_elastic_world_size",
    "Current world size of the elastic training worker group",
)

# ---------------------------------------------------------------- profiler
# Live step-time attribution from ray_tpu.train.profiler: every step's
# wall clock split into data-wait / h2d / compute / collective / ckpt-block
# buckets, plus the derived MFU / tokens-per-second / starvation gauges
# the multi-chip MFU push and the metrics-driven autoscaler consume.

STEPS_PROFILED = Counter(
    "ray_tpu_train_steps_total",
    "Training steps closed by the step profiler (report() boundaries)",
)

STEP_SECONDS = Histogram(
    "ray_tpu_train_step_seconds",
    "Wall seconds per training step, report() to report()",
    boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)

STEP_P50_SECONDS = Gauge(
    "ray_tpu_train_step_p50_seconds",
    "Median step wall time over the profiler's recent-step window",
)

STEP_P95_SECONDS = Gauge(
    "ray_tpu_train_step_p95_seconds",
    "95th-percentile step wall time over the profiler's recent-step window",
)

STEP_BUCKET_SECONDS = Gauge(
    "ray_tpu_train_step_bucket_seconds",
    "Last step's wall-time attribution per bucket (data_wait / h2d / "
    "compute / collective / ckpt_block); buckets sum to the step wall",
    ("bucket",),
)

DATA_STARVED_FRACTION = Gauge(
    "ray_tpu_train_data_starved_fraction",
    "Fraction of the last step's wall time spent blocked on the input "
    "pipeline (the per-step view of ingest starved-seconds)",
)

TOKENS_PER_SECOND = Gauge(
    "ray_tpu_train_tokens_per_second",
    "Training throughput from the step profiler (requires "
    "profiler.configure(tokens_per_step=...))",
)

MFU = Gauge(
    "ray_tpu_train_mfu",
    "Model FLOPs utilization of the last step: flops_per_step / wall / "
    "peak_flops (requires profiler.configure(flops_per_step=..., "
    "peak_flops=...))",
)
