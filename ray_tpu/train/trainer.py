"""Trainers + the training controller.

Modeled on the reference's Train v2 architecture (ref: python/ray/train/v2/
_internal/execution/controller.py:73 TrainController — a standalone control
loop polling a WorkerGroup, with ScalingPolicy/FailurePolicy), rather than
Train v1's route through a single-trial Tune run (ref: base_trainer.py:608).
Workers are actors (ref: _internal/worker_group.py:102 WorkerGroup,
RayTrainWorker:19); on a TPU host they are thread actors sharing the one JAX
client, and gradient sync happens either through ray_tpu.collective (SPMD
mode) or inside a pjit'd step the user writes against the mesh (mesh mode).

Elastic recovery (ref: v2 FailurePolicy): a worker failure tears down the
group, and the whole group restarts from the latest registered checkpoint —
delivered to workers via train.get_checkpoint().  With
``ScalingConfig(elastic=ElasticConfig(...))`` the world size itself is
dynamic: a preemption shrinks the group to surviving capacity, restores the
last committed step from the in-memory replica tier (disk as the floor),
reshards the data through the exactly-once sample ledger
(train/elastic.py) and resumes inside the same fit(); when capacity comes
back the group grows again at the next checkpoint boundary
(docs/elastic-training.md).

NOTE on thread workers + JAX: calls into *jitted* functions are thread-safe
and release the GIL; concurrent *eager* jax ops from many worker threads can
race inside jax's dispatch on some backends.  Keep per-step math inside jit
(which you want for performance anyway) — see tests/test_train.py
test_multi_worker_allreduce_training for the pattern.
"""

from __future__ import annotations

import queue
import tempfile
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import collective
from ray_tpu._private import fault_injection
from ray_tpu.exceptions import RayTpuError, TaskError
from ray_tpu.train import metrics as train_metrics
from ray_tpu.train import run_registry
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import DatasetConfig, RunConfig, ScalingConfig
from ray_tpu.train.elastic import ElasticDatasetShard, SampleLedger
from ray_tpu.train.profiler import StepProfiler
from ray_tpu.train.session import TrainContext, TrainSession, clear_session, init_session
from ray_tpu.util import flight_recorder, tracing
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


class Result:
    """(ref: python/ray/train/result.py Result)"""

    def __init__(self, metrics: Optional[Dict[str, Any]], checkpoint: Optional[Checkpoint],
                 path: str, error: Optional[BaseException] = None,
                 metrics_history: Optional[List[Dict[str, Any]]] = None,
                 elastic_events: Optional[List[Dict[str, Any]]] = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error
        self.metrics_history = metrics_history or []
        #: shrink/grow/recovery records from elastic training (empty unless
        #: ScalingConfig.elastic): type, from_world/to_world, restore_step,
        #: lost_steps, requeued_samples, recovery_seconds.
        self.elastic_events = elastic_events or []

    def __repr__(self) -> str:
        return f"Result(metrics={self.metrics}, checkpoint={self.checkpoint}, error={self.error})"


def invoke_train_loop(train_loop: Callable,
                      loop_config: Optional[Dict[str, Any]]) -> None:
    """Signature-dispatch shared by every worker kind (ref: the reference
    accepts both `def loop()` and `def loop(config)`)."""
    import inspect

    sig = inspect.signature(train_loop)
    if len(sig.parameters) >= 1:
        train_loop(loop_config or {})
    else:
        train_loop()


@ray_tpu.remote
class TrainWorker:
    """(ref: _internal/worker_group.py:19 RayTrainWorker)"""

    def __init__(self, rank: int, world_size: int, group_name: str):
        self.rank = rank
        self.world_size = world_size
        # Multi-host: join the jax.distributed cluster when the operator set
        # RAY_TPU_COORDINATOR/... on the worker env (the DCN-tier bootstrap;
        # ref: train/torch/config.py:66 _setup_torch_process_group).
        from ray_tpu.collective import distributed

        distributed.auto_initialize()
        collective.init_collective_group(world_size, rank, backend="xla",
                                         group_name=group_name)

    def run(self, train_loop: Callable, loop_config: Optional[Dict[str, Any]],
            session: TrainSession) -> str:
        # Chaos: a worker dying right at run entry (the other half of the
        # per-report() consultation in TrainSession.report).
        fault_injection.check("train_worker_run")
        init_session(session)
        try:
            invoke_train_loop(train_loop, loop_config)
            return "done"
        except StopIteration:
            return "stopped"
        finally:
            clear_session()


def _node_ip() -> str:
    """Best-effort routable IP of this host (ref: ray._private.services
    get_node_ip_address — UDP-connect trick, no packets sent)."""
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _reserve_addr() -> str:
    """Probe a free port on this host and return "ip:port" — the rendezvous
    address a rank-0 worker advertises (jax.distributed coordinator / gloo
    master).  TOCTOU-racy by nature; the consumers bound their rendezvous
    with timeouts for exactly that reason."""
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return f"{_node_ip()}:{port}"


def _drive_worker_refs(refs, drain) -> None:
    """Poll a worker group's run() refs to completion, draining the report
    channel as results stream in; re-raises the first worker error (shared
    by the process-tier controllers — torch and jax.distributed)."""
    pending = list(refs)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=len(pending),
                                      timeout=0.05)
        drain()
        for r in ready:
            ray_tpu.get(r)  # raise worker errors here
    drain()


class DistTrainSession:
    """Pickle-safe session for multi-host workers: report() ships metrics +
    the checkpoint directory BY VALUE (tar.gz) through an actor-backed queue
    — worker processes live on other machines, so neither the thread tier's
    in-memory queue nor bare paths can cross (ref: _TrainSession:112
    contract; train/_internal/storage.py checkpoint upload)."""

    def __init__(self, context: TrainContext, report_queue,
                 checkpoint_to_restore: Optional[Checkpoint] = None):
        self.context = context
        self._queue = report_queue
        self.checkpoint_to_restore = checkpoint_to_restore
        self.dataset_shards: Dict[str, Any] = {}
        self.stop_requested = threading.Event()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        from ray_tpu.train.checkpoint import pack_checkpoint

        self._queue.put({
            "rank": self.context.world_rank,
            "metrics": dict(metrics),
            "checkpoint_blob": pack_checkpoint(checkpoint),
        })


@ray_tpu.remote
class JaxDistTrainWorker:
    """One jax.distributed rank in its own OS process.

    The multi-host worker tier (ref: _internal/backend_executor.py:69 — the
    worker group's actors span nodes and are bootstrapped into one process
    group; train/torch/config.py:66,115 _setup_torch_process_group).  Here
    the process group is JAX's multi-controller runtime: after setup(),
    jax.devices() on every worker is the GLOBAL device set, meshes span the
    cluster, and ray_tpu.collective ops compile to global SPMD programs
    (collective/dcn_group.py).  Always created with isolation='process'."""

    def __init__(self, rank: int, world_size: int, group_name: str):
        self.rank = rank
        self.world = world_size
        self.group_name = group_name

    def reserve_coordinator(self) -> str:
        """Rank 0 picks the jax.distributed coordinator address on ITS host."""
        return _reserve_addr()

    def setup(self, coordinator: str) -> Dict[str, Any]:
        """Join the multi-controller cluster; returns topology for sanity
        checks.  Called CONCURRENTLY on all ranks (initialize barriers)."""
        from ray_tpu.collective import distributed

        distributed.initialize(coordinator, self.world, self.rank)
        collective.init_collective_group(self.world, self.rank, backend="xla",
                                         group_name=self.group_name)
        import jax

        return {"rank": self.rank, "process_count": jax.process_count(),
                "global_devices": len(jax.devices())}

    def run(self, train_loop: Callable, loop_config: Optional[Dict[str, Any]],
            context: TrainContext, report_queue,
            restore_blob: Optional[bytes]) -> str:
        import shutil

        from ray_tpu.train.checkpoint import unpack_checkpoint

        restore = unpack_checkpoint(restore_blob)
        session = DistTrainSession(context, report_queue, restore)
        init_session(session)
        try:
            invoke_train_loop(train_loop, loop_config)
            return "done"
        finally:
            clear_session()
            if restore is not None:
                # The unpacked restore dir is this attempt's scratch copy —
                # N workers x N restarts of model-sized leaks otherwise.
                shutil.rmtree(restore.path, ignore_errors=True)

    def teardown(self) -> None:
        collective.destroy_collective_group(self.group_name)
        from ray_tpu.collective import distributed

        distributed.shutdown()


class DataParallelTrainer:
    """(ref: python/ray/train/data_parallel_trainer.py:25)"""

    _collective_counter = 0

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_config: Optional[DatasetConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.dataset_config = dataset_config or DatasetConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        # Elastic recovery clock: set at failure/grow time, observed by
        # _drain_sessions when the first report of the resumed attempt
        # lands (kill -> training-resumed latency).
        self._recovery_t0: Optional[float] = None
        self._recovery_event: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        run_name = self.run_config.name or f"train_{int(time.time())}"
        storage = self.run_config.storage_path or tempfile.mkdtemp(prefix="ray_tpu_train_")
        import os

        experiment_path = os.path.join(storage, run_name)
        ckpt_conf = self.run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(experiment_path, "checkpoints"),
            num_to_keep=ckpt_conf.num_to_keep,
            score_attribute=ckpt_conf.checkpoint_score_attribute,
            score_order=ckpt_conf.checkpoint_score_order,
        )

        # Async checkpointing (CheckpointConfig.async_save): a
        # CheckpointCoordinator actor owns the same checkpoints dir and
        # two-phase-commits sharded saves flowing out of report(checkpoint=
        # <pytree>); restarts restore from its latest committed step.
        coordinator = None
        if ckpt_conf.async_save:
            from ray_tpu._private.runtime import get_runtime
            from ray_tpu.checkpoint import CheckpointCoordinator
            from ray_tpu.util.scheduling_strategies import (
                NodeAffinitySchedulingStrategy,
            )

            # The coordinator owns its own subdirectory: it and the legacy
            # CheckpointManager assign checkpoint_NNNNNN names from
            # independent counters, so sharing one directory would let
            # either side clobber or retention-delete the other's dirs.
            # Pinned to the head node (where this controller lives): a
            # preempted worker node must never take the commit authority
            # with it — elastic recovery asks it what step to restore.
            coordinator = ray_tpu.remote(CheckpointCoordinator).options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    str(get_runtime().head_node_id), soft=True),
            ).remote(
                os.path.join(experiment_path, "checkpoints", "sharded"),
                keep=ckpt_conf.num_to_keep,
                replica_steps=ckpt_conf.replica_memory_steps)

        scfg = self.scaling_config
        elastic = scfg.elastic
        cur_world = scfg.num_workers
        elastic_events: List[Dict[str, Any]] = []
        # State API: the run is visible to list_train_runs() (and the
        # /api/train_runs route) for its whole lifetime — world size,
        # committed step and elastic events are kept current below.
        run_registry.register_run(run_name, world_size=cur_world,
                                  target_world=cur_world,
                                  path=experiment_path,
                                  elastic=elastic is not None)
        attempt_no = 1
        self._recovery_t0 = None
        self._recovery_event = None
        # Elastic data plane: every sized dataset becomes a shared
        # exactly-once ledger that outlives individual attempts — exclusive
        # claiming IS the reshard (see train/elastic.py).  Streaming
        # datasets keep the legacy per-world split.
        ledgers: Dict[str, SampleLedger] = {}
        if elastic is not None:
            for name, ds in self.datasets.items():
                if (not hasattr(ds, "streaming_split")
                        and hasattr(ds, "__len__")
                        and hasattr(ds, "__getitem__")):
                    ledgers[name] = SampleLedger(
                        ds, seal_on_claim=coordinator is None)
        #: exposed for inspection (chaos tests assert the per-sample
        #: exactly-once ledger after fit() returns)
        self.sample_ledgers = ledgers
        # Streaming data plane (docs/data-ingestion.md): with
        # DatasetConfig(streaming=True) — the default — every lazy Dataset
        # becomes a StreamingIngest shared across attempts: workers claim
        # source shards through a per-epoch ledger (claiming IS the
        # resplit under elastic world changes) and stream them through
        # backpressure -> windowed shuffle -> rebatch -> prefetch.
        dcfg = self.dataset_config
        ingests: Dict[str, Any] = {}
        if dcfg.streaming:
            from ray_tpu.data.ingest import StreamingIngest

            for name, ds in self.datasets.items():
                if name not in ledgers and hasattr(ds, "_op"):
                    ingests[name] = StreamingIngest(
                        ds,
                        window_blocks=dcfg.shuffle_window_blocks,
                        window_bytes=dcfg.window_bytes,
                        seed=dcfg.shuffle_seed,
                        prefetch_batches=dcfg.prefetch_batches,
                        seal_on_claim=coordinator is None)
        #: exposed for inspection (tests audit per-shard exactly-once
        #: accounting after fit() returns)
        self.streaming_ingests = ingests

        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        restore_ckpt = self.resume_from_checkpoint
        last_restore_step: Optional[int] = None
        last_error: Optional[BaseException] = None
        history: List[Dict[str, Any]] = []

        try:
            while True:
                outcome = self._run_attempt(run_name, manager, restore_ckpt,
                                            experiment_path, coordinator,
                                            world=cur_world, ledgers=ledgers,
                                            ingests=ingests)
                history.extend(outcome["history"])
                if outcome["status"] == "finished":
                    run_registry.finish_run(run_name, "finished")
                    for ledger in ledgers.values():
                        ledger.seal_all()  # clean finish: nothing rolls back
                    for ingest in ingests.values():
                        # Not seal_all: shard claims the prefetch pump made
                        # but whose batches the user loop never consumed
                        # (a fixed-steps loop breaking out of iter_batches)
                        # roll back so audit() never reports them trained.
                        ingest.finish()
                    return Result(
                        metrics=outcome["last_metrics"],
                        checkpoint=(manager.latest_checkpoint()
                                    or self._coordinator_checkpoint(
                                        coordinator, from_memory=False)),
                        path=experiment_path,
                        # Surfaces e.g. "every async save failed": training
                        # succeeded but the run has no usable checkpoint.
                        error=outcome["error"],
                        metrics_history=history,
                        elastic_events=elastic_events,
                    )
                if outcome["status"] == "grow":
                    # Capacity came back and every worker stopped cleanly at
                    # a checkpoint boundary: restore from the committed step
                    # (its save drained before we got here) and restart the
                    # attempt at the bigger world.  Not a failure.
                    new_world = outcome["new_world"]
                    restore_ckpt, step = self._elastic_restore_point(
                        coordinator, manager)
                    for ledger in ledgers.values():
                        ledger.rollback(step)
                    for ingest in ingests.values():
                        ingest.rollback(step)
                    train_metrics.GROW_EVENTS.inc()
                    event = {"type": "grow", "from_world": cur_world,
                             "to_world": new_world, "restore_step": step,
                             "time": time.time()}
                    elastic_events.append(event)
                    run_registry.record_event(run_name, event)
                    self._recovery_t0 = time.monotonic()
                    self._recovery_event = event
                    cur_world = new_world
                    attempt_no += 1
                    run_registry.update_run(run_name, attempts=attempt_no)
                    if step is not None:
                        last_restore_step = step
                    continue
                last_error = outcome["error"]
                fatal = outcome["status"] == "fatal"
                handled = False
                if not fatal and elastic is not None:
                    from ray_tpu.autoscaler.elastic import worker_capacity

                    # Shrink (or hold) the world to what the live cluster
                    # can host, restore the last committed step — memory
                    # replicas first — and requeue every rolled-back claim.
                    cap = worker_capacity(scfg.worker_resources())
                    target = max(elastic.min_workers,
                                 min(cap, elastic.resolve_max(scfg.num_workers)))
                    restore_ckpt, step = self._elastic_restore_point(
                        coordinator, manager)
                    if restore_ckpt is None:
                        restore_ckpt = self.resume_from_checkpoint
                    requeued = sum(ledger.rollback(step)
                                   for ledger in ledgers.values())
                    requeued += sum(ingest.rollback(step)
                                    for ingest in ingests.values())
                    last_step = outcome.get("last_step")
                    lost = 0
                    if last_step is not None:
                        lost = max(0, last_step
                                   - (step if step is not None else -1))
                    train_metrics.LOST_STEPS.inc(lost)  # inc(0) is a no-op
                    if target < cur_world:
                        train_metrics.SHRINK_EVENTS.inc()
                    event = {"type": "shrink" if target < cur_world else "recover",
                             "from_world": cur_world, "to_world": target,
                             "restore_step": step, "lost_steps": lost,
                             "requeued_samples": requeued, "time": time.time()}
                    elastic_events.append(event)
                    run_registry.record_event(run_name, event)
                    # Preemption forensics: snapshot the black box before
                    # the recovery attempt overwrites the ring — the dump
                    # carries the failed attempt's final train spans and
                    # every thread's stack (best-effort, flood-controlled).
                    flight_recorder.trigger_dump("elastic_preempt", {
                        "run": run_name, "event": event,
                        "error": str(last_error) if last_error else "",
                    })
                    self._recovery_t0 = outcome.get("failed_at") or time.monotonic()
                    self._recovery_event = event
                    cur_world = target
                    # A recovery only "handles" the failure when the cluster
                    # can still run AND the restore point advanced since the
                    # last one — repeated failures pinned to the same step
                    # burn max_failures like any other crash loop.
                    progressed = step is not None and (
                        last_restore_step is None or step > last_restore_step)
                    if step is not None:
                        last_restore_step = step
                    handled = cap >= elastic.min_workers and progressed
                if not handled:
                    failures += 1
                exhausted = max_failures >= 0 and failures > max_failures
                # "fatal" = retrying cannot help (e.g. infeasible resources):
                # return even under max_failures=-1 instead of spinning forever.
                if exhausted or fatal:
                    run_registry.finish_run(run_name, "failed")
                    return Result(
                        metrics=outcome["last_metrics"],
                        checkpoint=(manager.latest_checkpoint()
                                    or self._coordinator_checkpoint(
                                        coordinator, from_memory=False)),
                        path=experiment_path,
                        error=last_error,
                        metrics_history=history,
                        elastic_events=elastic_events,
                    )
                if elastic is not None:
                    time.sleep(0.05)  # resume fast — recovery latency is the product
                else:
                    time.sleep(min(2.0 ** min(failures, 5) * 0.1, 5.0))  # restart backoff
                    # Restart from the latest checkpoint (ref: v2 controller
                    # RESTARTING state).  The coordinator's committed step
                    # wins — its replica tier restores without re-reading
                    # storage; the legacy manager path is the fallback.
                    restore_ckpt = (self._coordinator_checkpoint(coordinator)
                                    or manager.latest_checkpoint()
                                    or self.resume_from_checkpoint)
                    # The restarted attempt re-runs the user loop from its
                    # own epoch 0: ingest epochs must start fresh too.
                    for ingest in ingests.values():
                        ingest.reset()
                attempt_no += 1
                run_registry.update_run(run_name, attempts=attempt_no)
        finally:
            # Device-telemetry rollup for the run row (compile history,
            # pool high-water, transfer tail) — best-effort, the registry
            # write must never mask the real exit path.
            try:
                from ray_tpu.util import device_telemetry

                run_registry.update_run(
                    run_name,
                    device_telemetry=device_telemetry.snapshot())
            except Exception:
                pass
            # A raise out of the attempt loop (controller bug, KeyboardInterrupt)
            # must not leave the registry row "running" forever.
            row = run_registry.get_run(run_name)
            if row is not None and row["status"] == "running":
                run_registry.finish_run(run_name, "failed")
            if coordinator is not None:
                try:
                    ray_tpu.kill(coordinator)
                except Exception:
                    pass

    # ------------------------------------------------ coordinator restore
    def _coordinator_checkpoint(self, coordinator,
                                from_memory: bool = True) -> Optional[Checkpoint]:
        """Checkpoint handle for the coordinator's latest committed step.

        Prefers the in-memory replica tier (full shard set resident):
        payloads are materialized into a fresh local committed dir, so the
        handle's to_pytree() never touches the original storage — the
        Gemini-style fast recovery path.  When the writers' node died WITH
        its object store, the peer ReplicaHolder's copies are next; the
        committed dir on storage is the floor."""
        if coordinator is None:
            return None
        try:
            src = ray_tpu.get(coordinator.restore_source.remote(), timeout=30)
        except Exception:
            return None
        if src is None:
            return None
        if from_memory:
            ckpt = (self._materialize_memory(src)
                    or self._materialize_peer(coordinator, src["step"]))
            if ckpt is not None:
                return ckpt
        return Checkpoint(src["path"])

    def _elastic_restore_point(self, coordinator, manager: CheckpointManager):
        """(checkpoint, step) to resume from after a preemption or grow:
        memory replicas -> peer holder payloads -> committed dir on disk ->
        legacy manager checkpoints (step unknown there).  Every remote
        fetch is bounded, so a dead holder or a lost object-store ref
        falls through to the next tier instead of hanging the recovery."""
        if coordinator is not None:
            try:
                src = ray_tpu.get(coordinator.restore_source.remote(),
                                  timeout=30)
            except Exception:
                src = None
            if src is not None:
                step = src["step"]
                ckpt = (self._materialize_memory(src)
                        or self._materialize_peer(coordinator, step))
                return (ckpt if ckpt is not None
                        else Checkpoint(src["path"])), step
        ckpt = manager.latest_checkpoint()
        return (ckpt, None) if ckpt is not None else (None, None)

    def _materialize_memory(self, src: Dict) -> Optional[Checkpoint]:
        """Local committed dir built from the object-store replica refs;
        None when the set is absent or any ref is unfetchable (its pinning
        node died) within the bound."""
        if not src.get("replicas"):
            return None
        try:
            from ray_tpu.checkpoint import materialize_from_payloads
            from ray_tpu.checkpoint import metrics as _ckpt_metrics

            refs = src["replicas"]["refs"]
            payloads = {int(sid): ray_tpu.get(w["ref"], timeout=20)
                        for sid, w in refs.items()}
            local_root = tempfile.mkdtemp(prefix="ray_tpu_ckpt_mem_")
            path = materialize_from_payloads(local_root, src["step"], payloads)
            _ckpt_metrics.RESTORES.inc(tags={"source": "memory"})
            return Checkpoint(path)
        except Exception:
            return None

    def _materialize_peer(self, coordinator, step: int) -> Optional[Checkpoint]:
        """Same, from the ReplicaHolder actor on a peer node — the tier
        that survives the writers' own node being preempted."""
        try:
            res = ray_tpu.get(coordinator.peer_payloads.remote(step),
                              timeout=30)
        except Exception:
            return None
        if not res:
            return None
        try:
            from ray_tpu.checkpoint import materialize_from_payloads
            from ray_tpu.checkpoint import metrics as _ckpt_metrics

            payloads = {int(sid): p for sid, p in res["payloads"].items()}
            local_root = tempfile.mkdtemp(prefix="ray_tpu_ckpt_peer_")
            path = materialize_from_payloads(local_root, res["step"], payloads)
            _ckpt_metrics.RESTORES.inc(tags={"source": "peer"})
            return Checkpoint(path)
        except Exception:
            return None

    def _dead_workers(self, workers) -> List[int]:
        """Ranks whose worker actor is no longer ALIVE (killed or its node
        preempted)."""
        from ray_tpu._private.runtime import get_runtime

        runtime = get_runtime()
        dead = []
        for rank, w in enumerate(workers):
            try:
                state = runtime.get_actor_state(w._ray_actor_id)
            except Exception:
                continue
            if state is None or state.state == "DEAD":
                # PENDING_CREATION/ALIVE are healthy; RESTARTING resolves
                # through the actor's own restart FSM, not ours.
                dead.append(rank)
        return dead

    def _committed_step(self, coordinator) -> Optional[int]:
        if coordinator is None:
            return None
        try:
            return ray_tpu.get(coordinator.latest_committed.remote(),
                               timeout=10)
        except Exception:
            return None

    def _preempt_worker_node(self, pg) -> Optional[str]:
        """The preempt_node chaos hook: take out a whole node hosting
        worker-group bundles (never the head — the controller lives there)."""
        from ray_tpu._private.runtime import get_runtime
        from ray_tpu.autoscaler.elastic import simulate_preemption

        head = str(get_runtime().head_node_id)
        victim = next((str(n) for n in pg.bundle_node_ids()
                       if n is not None and str(n) != head), None)
        return simulate_preemption(victim)

    # ---------------------------------------------------------- one attempt
    def _run_attempt(self, run_name: str, manager: CheckpointManager,
                     restore_ckpt: Optional[Checkpoint], experiment_path: str,
                     coordinator=None, world: Optional[int] = None,
                     ledgers: Optional[Dict[str, SampleLedger]] = None,
                     ingests: Optional[Dict[str, Any]] = None) -> Dict:
        scfg = self.scaling_config
        if world is None:
            world = scfg.num_workers
        if scfg.elastic is not None:
            # Stable group name + atomic reform: any rank of a preempted
            # attempt still blocked in a rendezvous wakes with an error,
            # and the group's world size tracks the elastic world.
            group_name = f"train-{run_name}"
            collective.reform_collective_group(world, group_name=group_name)
        else:
            DataParallelTrainer._collective_counter += 1
            group_name = f"train-{run_name}-{DataParallelTrainer._collective_counter}"

        # Gang-schedule the worker group via a placement group
        # (ref: backend_executor.py placement group per worker group).
        bundles = [scfg.worker_resources() for _ in range(world)]
        # Infeasible-by-construction requests fail immediately, not after the
        # reservation timeout.
        from ray_tpu._private.runtime import get_runtime
        from ray_tpu._private.scheduling import res_fits

        nodes = get_runtime().scheduler.nodes()
        for bundle in bundles:
            if not any(res_fits(n.total, bundle) for n in nodes if n.alive):
                return {"status": "fatal", "last_metrics": None, "history": [],
                        "error": RuntimeError(
                            f"Worker bundle {bundle} fits no node in the cluster "
                            f"(total: {ray_tpu.cluster_resources()})")}
        pg = placement_group(bundles, strategy=scfg.placement_strategy)
        try:
            if not pg.wait(timeout_seconds=60):
                total = ray_tpu.cluster_resources()
                return {"status": "failed", "last_metrics": None, "history": [],
                        "error": RuntimeError(
                            f"Could not reserve {world}x{scfg.worker_resources()} "
                            f"for the worker group within 60s (cluster: {total}). "
                            f"Reduce num_workers/resources_per_worker or add nodes.")}
            return self._run_with_pg(pg, run_name, group_name, manager,
                                     restore_ckpt, coordinator, world=world,
                                     ledgers=ledgers, ingests=ingests)
        finally:
            collective.destroy_collective_group(group_name)
            remove_placement_group(pg)

    def _worker_mode(self, pg) -> str:
        """threads (one TPU host, shared JAX client) vs processes (one
        jax.distributed rank per worker process — required once the worker
        group spans nodes: a thread here cannot execute on another host)."""
        mode = getattr(self.scaling_config, "worker_mode", "auto")
        if mode in ("threads", "processes"):
            return mode
        if mode != "auto":
            raise ValueError(f"worker_mode must be auto|threads|processes, got {mode!r}")
        from ray_tpu._private.runtime import get_runtime

        head = str(get_runtime().head_node_id)
        return "processes" if any(
            n is not None and n != head for n in pg.bundle_node_ids()
        ) else "threads"

    def _run_with_pg(self, pg, run_name: str, group_name: str,
                     manager: CheckpointManager, restore_ckpt,
                     coordinator=None, world: Optional[int] = None,
                     ledgers: Optional[Dict[str, SampleLedger]] = None,
                     ingests: Optional[Dict[str, Any]] = None) -> Dict:
        if self._worker_mode(pg) == "processes":
            if self.scaling_config.elastic is not None:
                return {"status": "fatal", "last_metrics": None, "history": [],
                        "error": ValueError(
                            "elastic training requires thread-tier workers "
                            "(the sample ledger and replica restore live in "
                            "the controller's process); use ScalingConfig("
                            "worker_mode='threads')")}
            # Process-tier workers ship checkpoints by value through the
            # report queue; the async sharded path is thread-tier only.
            return self._run_distributed(pg, run_name, group_name, manager,
                                         restore_ckpt)
        scfg = self.scaling_config
        elastic = scfg.elastic
        if world is None:
            world = scfg.num_workers
        ledgers = ledgers or {}
        ingests = ingests or {}
        train_metrics.WORLD_SIZE.set(world)
        run_registry.update_run(run_name, world_size=world)
        dataset_shards = self._split_datasets(
            world, exclude=set(ledgers) | set(ingests))
        writers: List = []
        epoch = 0
        start_step = 0
        if coordinator is not None:
            from ray_tpu.checkpoint import ShardWriter

            # New attempt = new epoch: shards from a crashed attempt's
            # in-flight saves can no longer mix into this attempt's steps.
            epoch = ray_tpu.get(coordinator.new_epoch.remote(), timeout=30)
            latest = ray_tpu.get(coordinator.latest_committed.remote(),
                                 timeout=30)
            start_step = (latest + 1) if latest is not None else 0
            writers = [ShardWriter(coordinator, shard_id=rank,
                                   world_size=world, epoch=epoch)
                       for rank in range(world)]
        sessions: List[TrainSession] = []
        workers = []
        for rank in range(world):
            ctx = TrainContext(world_rank=rank, world_size=world, local_rank=rank,
                               trial_name=run_name, experiment_name=run_name,
                               group_name=group_name)
            session = TrainSession(ctx, checkpoint_to_restore=restore_ckpt,
                                   dataset_shards=dataset_shards[rank],
                                   shard_writer=writers[rank] if writers else None,
                                   start_step=start_step,
                                   dataset_config=self.dataset_config,
                                   profiler=(StepProfiler(run_name=run_name,
                                                          rank=rank)
                                             if self.run_config.profile
                                             else None))
            # Elastic datasets are views onto the shared ledger, bound to
            # THIS session so claims carry its next checkpoint step.
            for name, ledger in ledgers.items():
                session.dataset_shards[name] = ElasticDatasetShard(ledger, session)
            # Streaming datasets: a per-session view onto the shared
            # ingest — shard claims carry this session's checkpoint step.
            for name, ingest in ingests.items():
                session.dataset_shards[name] = ingest.make_shard(session)
            sessions.append(session)
            workers.append(
                TrainWorker.options(
                    resources=scfg.worker_resources(),
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=rank),
                ).remote(rank, world, group_name)
            )

        refs = [
            w.run.remote(self.train_loop, self.train_loop_config, s)
            for w, s in zip(workers, sessions)
        ]

        history: List[Dict[str, Any]] = []
        last_metrics: Optional[Dict[str, Any]] = None
        pending = list(refs)
        statuses: List[str] = []
        injector = fault_injection.get_injector()
        grow_target: Optional[int] = None
        desired_max = (elastic.resolve_max(scfg.num_workers)
                       if elastic is not None else world)
        last_seal = 0.0
        last_health = 0.0
        last_grow_check = time.monotonic()
        grow_first_exit: Optional[float] = None
        grow_woke = False
        try:
            while pending:
                ready, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.05)
                last_metrics, new_rows = self._drain_sessions(sessions, manager, last_metrics)
                history.extend(new_rows)
                now = time.monotonic()
                # Liveness: a preempted thread-tier worker's actor dies but
                # its in-flight run() thread does NOT — the ref would never
                # resolve, so the controller polls actor health itself (the
                # same signal serve's health machinery uses).
                if now - last_health >= 0.25:
                    last_health = now
                    dead = self._dead_workers(workers)
                    if dead:
                        from ray_tpu.exceptions import WorkerCrashedError

                        raise WorkerCrashedError(
                            f"{len(dead)} train worker(s) died "
                            f"(ranks {sorted(dead)}; node preempted?)")
                # Seal provisional ledger claims as the coordinator commits
                # their steps: sealed samples never requeue on a rollback.
                if ((ledgers or ingests) and coordinator is not None
                        and now - last_seal >= 0.25):
                    last_seal = now
                    committed = self._committed_step(coordinator)
                    if committed is not None:
                        run_registry.update_run(
                            run_name, last_committed_step=committed)
                        for ledger in ledgers.values():
                            ledger.seal(committed)
                        for ingest in ingests.values():
                            ingest.seal(committed)
                # Chaos: a whole worker node vanishes (TPU slice preempted).
                if injector.enabled and injector.fires("preempt_node"):
                    self._preempt_worker_node(pg)
                # Grow back toward the target world at a checkpoint boundary
                # once capacity returns (and there is a step to restore —
                # growing without one would mean training from scratch).
                if (elastic is not None and grow_target is None
                        and world < desired_max
                        and now - last_grow_check >= elastic.grow_check_period_s):
                    last_grow_check = now
                    from ray_tpu.autoscaler.elastic import worker_capacity

                    target = min(worker_capacity(scfg.worker_resources()),
                                 desired_max)
                    has_restore = (self._committed_step(coordinator) is not None
                                   or manager.latest_checkpoint() is not None)
                    if target > world and has_restore:
                        grow_target = target
                        # report() IS the checkpoint boundary: each worker
                        # raises StopIteration there and returns "stopped".
                        for s in sessions:
                            s.stop_requested.set()
                for r in ready:
                    try:
                        statuses.append(ray_tpu.get(r))  # raise worker errors
                    except (TaskError, RayTpuError):
                        if grow_target is None:
                            raise
                        # Interrupted mid-rendezvous by the boundary wake
                        # below: its uncommitted claims roll back with the
                        # grow restore, so this is a clean stop.
                        statuses.append("stopped")
                # Grow-stop liveness: workers observe the stop at different
                # lockstep points — one can exit at its report() while a
                # peer already entered the next collective and now waits on
                # a partner that will never arrive.  Once anyone has exited,
                # give the rest a grace window to reach their own boundary,
                # then wake them by destroying the group (their collective
                # raises; swallowed as "stopped" above).
                if grow_target is not None and statuses and pending:
                    if grow_first_exit is None:
                        grow_first_exit = now
                    elif not grow_woke and now - grow_first_exit >= 1.0:
                        grow_woke = True
                        try:
                            collective.get_collective_group(
                                group_name).destroy()
                        except ValueError:
                            pass
            # Final drain after workers exit.
            last_metrics, new_rows = self._drain_sessions(sessions, manager, last_metrics)
            history.extend(new_rows)
            # Async saves still persisting in the background belong to this
            # run: let them land (and commit) before declaring it finished —
            # and, on a grow, before the restore point is chosen.
            for wtr in writers:
                try:
                    wtr.drain(timeout=120)
                except Exception:
                    pass
                wtr.close()
            if (ledgers or ingests) and coordinator is not None:
                committed = self._committed_step(coordinator)
                if committed is not None:
                    run_registry.update_run(
                        run_name, last_committed_step=committed)
                    for ledger in ledgers.values():
                        ledger.seal(committed)
                    for ingest in ingests.values():
                        ingest.seal(committed)
            # A grow stop can surface two ways: workers that hit report()
            # raise StopIteration ("stopped"), but workers whose user loop
            # exits because the ledger fence returned None come back
            # "finished" — the ledger still holding work distinguishes that
            # from a genuine end-of-dataset finish.
            work_left = any(not led.exhausted() for led in ledgers.values()) \
                or any(not ing.exhausted() for ing in ingests.values())
            if grow_target is not None and ("stopped" in statuses or work_left):
                return {"status": "grow", "new_world": grow_target,
                        "last_metrics": last_metrics, "history": history,
                        "error": None}
            return {"status": "finished", "last_metrics": last_metrics,
                    "history": history,
                    "error": self._check_async_saves(sessions, coordinator)}
        except (TaskError, RayTpuError) as e:  # worker failed
            failed_at = time.monotonic()
            for s in sessions:
                s.stop_requested.set()
            # Wake any worker blocked in a collective rendezvous NOW (the
            # group destroy in the caller's finally would also do it, but
            # draining results first needs them unwedged).
            try:
                collective.get_collective_group(group_name).destroy()
            except ValueError:
                pass
            for w in workers:
                ray_tpu.kill(w)
            # Queued-but-unstarted async saves die with the attempt (their
            # epoch is stale anyway); an in-flight persist may still commit,
            # which is always safe — the step is fully written.
            for wtr in writers:
                wtr.close()
            # Keep results reported before the crash (checkpoints especially —
            # the restart resumes from the last one registered).
            last_metrics, new_rows = self._drain_sessions(sessions, manager, last_metrics)
            history.extend(new_rows)
            # Highest step any session reported (its save may or may not
            # have committed) — the elastic controller's lost-step count is
            # this minus the restore step.
            last_step = max((s._ckpt_step - 1 for s in sessions), default=-1)
            return {"status": "failed", "last_metrics": last_metrics,
                    "history": history, "error": e,
                    "failed_at": failed_at,
                    "last_step": last_step if last_step >= 0 else None}

    # ------------------------------------------------- multi-host attempt
    def _run_distributed(self, pg, run_name: str, group_name: str,
                         manager: CheckpointManager, restore_ckpt) -> Dict:
        """One attempt with process-tier workers spanning worker nodes.

        rank 0 reserves the jax.distributed coordinator on its own host,
        every worker joins with its placement-group rank, and the group's
        collectives become global SPMD programs (ref: backend_executor.py
        _setup_worker_group + torch/config.py:115 — the same
        coordinator-address + rank/world bootstrap, NCCL swapped for XLA)."""
        from ray_tpu.train.checkpoint import pack_checkpoint, unpack_checkpoint
        from ray_tpu.util.queue import Empty, Queue

        scfg = self.scaling_config
        world = scfg.num_workers
        if self.datasets:
            return {"status": "fatal", "last_metrics": None, "history": [],
                    "error": ValueError(
                        "datasets= require thread-tier workers (streaming "
                        "iterators cannot cross process boundaries); use "
                        "ScalingConfig(worker_mode='threads') or load data "
                        "inside the train_loop")}
        node_ids = pg.bundle_node_ids()
        node_order: List[Optional[str]] = []
        for n in node_ids:
            if n not in node_order:
                node_order.append(n)
        local_counter: Dict[Optional[str], int] = {}
        workers = []
        contexts: List[TrainContext] = []
        for rank in range(world):
            n = node_ids[rank] if rank < len(node_ids) else None
            local_rank = local_counter.get(n, 0)
            local_counter[n] = local_rank + 1
            contexts.append(TrainContext(
                world_rank=rank, world_size=world, local_rank=local_rank,
                node_rank=node_order.index(n), trial_name=run_name,
                experiment_name=run_name, group_name=group_name))
            workers.append(
                JaxDistTrainWorker.options(
                    isolation="process",
                    resources=scfg.worker_resources(),
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=rank),
                ).remote(rank, world, group_name))

        report_queue = Queue()
        history: List[Dict[str, Any]] = []
        last_metrics: Optional[Dict[str, Any]] = None

        def drain() -> None:
            nonlocal last_metrics
            while True:
                try:
                    item = report_queue.get_nowait()
                except Empty:
                    return
                if item.get("checkpoint_blob"):
                    # unpack lands in a ray_tpu_ckpt_ tempdir, which
                    # register() MOVES into managed storage (no double copy).
                    manager.register(unpack_checkpoint(item["checkpoint_blob"]),
                                     item["metrics"])
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    history.append(item["metrics"])

        try:
            coord = ray_tpu.get(workers[0].reserve_coordinator.remote(),
                                timeout=120)
            ray_tpu.get([w.setup.remote(coord) for w in workers], timeout=300)
            blob = pack_checkpoint(restore_ckpt)
            refs = [w.run.remote(self.train_loop, self.train_loop_config, ctx,
                                 report_queue, blob)
                    for w, ctx in zip(workers, contexts)]
            _drive_worker_refs(refs, drain)
            for w in workers:
                try:
                    ray_tpu.get(w.teardown.remote(), timeout=15)
                except Exception:
                    pass
            return {"status": "finished", "last_metrics": last_metrics,
                    "history": history, "error": None}
        except (TaskError, RayTpuError) as e:
            # A dead node/worker leaves the others wedged inside a global
            # SPMD collective; killing their processes (finally below) is
            # what unblocks the restart.
            drain()
            return {"status": "failed", "last_metrics": last_metrics,
                    "history": history, "error": e}
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            try:
                report_queue.shutdown()
            except Exception:
                pass

    def _check_async_saves(self, sessions: List[TrainSession],
                           coordinator) -> Optional[BaseException]:
        """Async saves fail out-of-band (drain deliberately swallows them so
        a later commit can supersede); a run where NO save ever committed
        must not finish silently with checkpoint=None and no error."""
        reported = sum(getattr(s, "async_saves_reported", 0) for s in sessions)
        if not reported or coordinator is None:
            return None
        from ray_tpu.checkpoint.writer import _invoke

        try:
            latest = _invoke(coordinator, "latest_committed")
        except Exception:
            return None
        if latest is not None:
            return None
        causes = []
        for s in sessions:
            handle = getattr(s, "last_save_handle", None)
            if handle is None:
                continue
            try:
                exc = handle.exception(timeout=0)
            except Exception:
                exc = None
            if exc is not None:
                causes.append(repr(exc))
        import logging

        err = RuntimeError(
            f"{reported} async checkpoint save(s) were reported but no step "
            "ever committed — the run finished without a usable checkpoint"
            + (f"; last shard errors: {causes}" if causes else ""))
        logging.getLogger(__name__).warning("%s", err)
        return err

    def _drain_sessions(self, sessions: List[TrainSession], manager: CheckpointManager,
                        last_metrics: Optional[Dict[str, Any]]):
        history = []
        drained = False
        for session in sessions:
            while True:
                try:
                    item = session.results.get_nowait()
                except queue.Empty:
                    break
                drained = True
                # Metrics history follows rank 0 (the reference's convention),
                # but checkpoints from ANY rank are registered — a loop where a
                # non-zero rank carries the checkpoint must not lose progress.
                if item["checkpoint"] is not None:
                    manager.register(item["checkpoint"], item["metrics"])
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    history.append(item["metrics"])
        # First report after an elastic recovery = training resumed: close
        # the kill->resumed clock.
        if drained and self._recovery_t0 is not None:
            dt = time.monotonic() - self._recovery_t0
            train_metrics.RECOVERY_SECONDS.observe(dt)
            ev = self._recovery_event or {}
            # Timeline lane: the whole failure->resumed window as one span,
            # so a trace shows shrink/grow gaps between train.step rows.
            now_w = time.time()
            tracing.record_span("train.elastic", now_w - dt, now_w,
                                attributes={"type": ev.get("type", ""),
                                            "from_world": ev.get("from_world"),
                                            "to_world": ev.get("to_world"),
                                            "restore_step": ev.get("restore_step")})
            if self._recovery_event is not None:
                self._recovery_event["recovery_seconds"] = dt
            self._recovery_t0 = None
            self._recovery_event = None
        return last_metrics, history

    def _split_datasets(self, world: int, exclude=()) -> List[Dict[str, Any]]:
        """Per-rank dataset shards (ref: StreamSplitDataIterator coordinated
        split for Train ingest, data/_internal/iterator/stream_split_iterator.py:31).
        Names in ``exclude`` are served by the elastic sample ledger instead."""
        shards: List[Dict[str, Any]] = [{} for _ in range(world)]
        for name, ds in self.datasets.items():
            if name in exclude:
                continue
            if hasattr(ds, "streaming_split"):
                its = ds.streaming_split(world)
                for rank in range(world):
                    shards[rank][name] = its[rank]
            else:
                for rank in range(world):
                    shards[rank][name] = ds
        return shards


class JaxTrainer(DataParallelTrainer):
    """The TPU trainer (BASELINE north star: `JaxTrainer` pinning workers to
    TPU processes).  Identical controller; workers join the 'xla' collective
    group so `ray_tpu.collective.allreduce` inside the loop compiles to psum
    over ICI, and `use_tpu=True` reserves chips per worker.

    Single host, the workers are threads sharing one JAX client (mesh mode).
    When the placement group lands workers on OTHER nodes (or
    ``ScalingConfig(worker_mode="processes")``), each worker becomes its own
    OS process joined into one jax.distributed cluster: jax.devices() spans
    every worker's chips, meshes ride ICI within a host and DCN across, and
    the same train_loop runs unchanged (multi-controller SPMD)."""
