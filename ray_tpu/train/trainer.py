"""Trainers + the training controller.

Modeled on the reference's Train v2 architecture (ref: python/ray/train/v2/
_internal/execution/controller.py:73 TrainController — a standalone control
loop polling a WorkerGroup, with ScalingPolicy/FailurePolicy), rather than
Train v1's route through a single-trial Tune run (ref: base_trainer.py:608).
Workers are actors (ref: _internal/worker_group.py:102 WorkerGroup,
RayTrainWorker:19); on a TPU host they are thread actors sharing the one JAX
client, and gradient sync happens either through ray_tpu.collective (SPMD
mode) or inside a pjit'd step the user writes against the mesh (mesh mode).

Elastic recovery (ref: v2 FailurePolicy): a worker failure tears down the
group, and the whole group restarts from the latest registered checkpoint —
delivered to workers via train.get_checkpoint().

NOTE on thread workers + JAX: calls into *jitted* functions are thread-safe
and release the GIL; concurrent *eager* jax ops from many worker threads can
race inside jax's dispatch on some backends.  Keep per-step math inside jit
(which you want for performance anyway) — see tests/test_train.py
test_multi_worker_allreduce_training for the pattern.
"""

from __future__ import annotations

import queue
import tempfile
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import collective
from ray_tpu.exceptions import RayTpuError, TaskError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.session import TrainContext, TrainSession, clear_session, init_session
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


class Result:
    """(ref: python/ray/train/result.py Result)"""

    def __init__(self, metrics: Optional[Dict[str, Any]], checkpoint: Optional[Checkpoint],
                 path: str, error: Optional[BaseException] = None,
                 metrics_history: Optional[List[Dict[str, Any]]] = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error
        self.metrics_history = metrics_history or []

    def __repr__(self) -> str:
        return f"Result(metrics={self.metrics}, checkpoint={self.checkpoint}, error={self.error})"


def invoke_train_loop(train_loop: Callable,
                      loop_config: Optional[Dict[str, Any]]) -> None:
    """Signature-dispatch shared by every worker kind (ref: the reference
    accepts both `def loop()` and `def loop(config)`)."""
    import inspect

    sig = inspect.signature(train_loop)
    if len(sig.parameters) >= 1:
        train_loop(loop_config or {})
    else:
        train_loop()


@ray_tpu.remote
class TrainWorker:
    """(ref: _internal/worker_group.py:19 RayTrainWorker)"""

    def __init__(self, rank: int, world_size: int, group_name: str):
        self.rank = rank
        self.world_size = world_size
        # Multi-host: join the jax.distributed cluster when the operator set
        # RAY_TPU_COORDINATOR/... on the worker env (the DCN-tier bootstrap;
        # ref: train/torch/config.py:66 _setup_torch_process_group).
        from ray_tpu.collective import distributed

        distributed.auto_initialize()
        collective.init_collective_group(world_size, rank, backend="xla",
                                         group_name=group_name)

    def run(self, train_loop: Callable, loop_config: Optional[Dict[str, Any]],
            session: TrainSession) -> str:
        init_session(session)
        try:
            invoke_train_loop(train_loop, loop_config)
            return "done"
        except StopIteration:
            return "stopped"
        finally:
            clear_session()


def _node_ip() -> str:
    """Best-effort routable IP of this host (ref: ray._private.services
    get_node_ip_address — UDP-connect trick, no packets sent)."""
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _reserve_addr() -> str:
    """Probe a free port on this host and return "ip:port" — the rendezvous
    address a rank-0 worker advertises (jax.distributed coordinator / gloo
    master).  TOCTOU-racy by nature; the consumers bound their rendezvous
    with timeouts for exactly that reason."""
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return f"{_node_ip()}:{port}"


def _drive_worker_refs(refs, drain) -> None:
    """Poll a worker group's run() refs to completion, draining the report
    channel as results stream in; re-raises the first worker error (shared
    by the process-tier controllers — torch and jax.distributed)."""
    pending = list(refs)
    while pending:
        ready, pending = ray_tpu.wait(pending, num_returns=len(pending),
                                      timeout=0.05)
        drain()
        for r in ready:
            ray_tpu.get(r)  # raise worker errors here
    drain()


class DistTrainSession:
    """Pickle-safe session for multi-host workers: report() ships metrics +
    the checkpoint directory BY VALUE (tar.gz) through an actor-backed queue
    — worker processes live on other machines, so neither the thread tier's
    in-memory queue nor bare paths can cross (ref: _TrainSession:112
    contract; train/_internal/storage.py checkpoint upload)."""

    def __init__(self, context: TrainContext, report_queue,
                 checkpoint_to_restore: Optional[Checkpoint] = None):
        self.context = context
        self._queue = report_queue
        self.checkpoint_to_restore = checkpoint_to_restore
        self.dataset_shards: Dict[str, Any] = {}
        self.stop_requested = threading.Event()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        from ray_tpu.train.checkpoint import pack_checkpoint

        self._queue.put({
            "rank": self.context.world_rank,
            "metrics": dict(metrics),
            "checkpoint_blob": pack_checkpoint(checkpoint),
        })


@ray_tpu.remote
class JaxDistTrainWorker:
    """One jax.distributed rank in its own OS process.

    The multi-host worker tier (ref: _internal/backend_executor.py:69 — the
    worker group's actors span nodes and are bootstrapped into one process
    group; train/torch/config.py:66,115 _setup_torch_process_group).  Here
    the process group is JAX's multi-controller runtime: after setup(),
    jax.devices() on every worker is the GLOBAL device set, meshes span the
    cluster, and ray_tpu.collective ops compile to global SPMD programs
    (collective/dcn_group.py).  Always created with isolation='process'."""

    def __init__(self, rank: int, world_size: int, group_name: str):
        self.rank = rank
        self.world = world_size
        self.group_name = group_name

    def reserve_coordinator(self) -> str:
        """Rank 0 picks the jax.distributed coordinator address on ITS host."""
        return _reserve_addr()

    def setup(self, coordinator: str) -> Dict[str, Any]:
        """Join the multi-controller cluster; returns topology for sanity
        checks.  Called CONCURRENTLY on all ranks (initialize barriers)."""
        from ray_tpu.collective import distributed

        distributed.initialize(coordinator, self.world, self.rank)
        collective.init_collective_group(self.world, self.rank, backend="xla",
                                         group_name=self.group_name)
        import jax

        return {"rank": self.rank, "process_count": jax.process_count(),
                "global_devices": len(jax.devices())}

    def run(self, train_loop: Callable, loop_config: Optional[Dict[str, Any]],
            context: TrainContext, report_queue,
            restore_blob: Optional[bytes]) -> str:
        import shutil

        from ray_tpu.train.checkpoint import unpack_checkpoint

        restore = unpack_checkpoint(restore_blob)
        session = DistTrainSession(context, report_queue, restore)
        init_session(session)
        try:
            invoke_train_loop(train_loop, loop_config)
            return "done"
        finally:
            clear_session()
            if restore is not None:
                # The unpacked restore dir is this attempt's scratch copy —
                # N workers x N restarts of model-sized leaks otherwise.
                shutil.rmtree(restore.path, ignore_errors=True)

    def teardown(self) -> None:
        collective.destroy_collective_group(self.group_name)
        from ray_tpu.collective import distributed

        distributed.shutdown()


class DataParallelTrainer:
    """(ref: python/ray/train/data_parallel_trainer.py:25)"""

    _collective_counter = 0

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    # ------------------------------------------------------------------ fit
    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        run_name = self.run_config.name or f"train_{int(time.time())}"
        storage = self.run_config.storage_path or tempfile.mkdtemp(prefix="ray_tpu_train_")
        import os

        experiment_path = os.path.join(storage, run_name)
        ckpt_conf = self.run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(experiment_path, "checkpoints"),
            num_to_keep=ckpt_conf.num_to_keep,
            score_attribute=ckpt_conf.checkpoint_score_attribute,
            score_order=ckpt_conf.checkpoint_score_order,
        )

        # Async checkpointing (CheckpointConfig.async_save): a
        # CheckpointCoordinator actor owns the same checkpoints dir and
        # two-phase-commits sharded saves flowing out of report(checkpoint=
        # <pytree>); restarts restore from its latest committed step.
        coordinator = None
        if ckpt_conf.async_save:
            from ray_tpu.checkpoint import CheckpointCoordinator

            # The coordinator owns its own subdirectory: it and the legacy
            # CheckpointManager assign checkpoint_NNNNNN names from
            # independent counters, so sharing one directory would let
            # either side clobber or retention-delete the other's dirs.
            coordinator = ray_tpu.remote(CheckpointCoordinator).remote(
                os.path.join(experiment_path, "checkpoints", "sharded"),
                keep=ckpt_conf.num_to_keep,
                replica_steps=ckpt_conf.replica_memory_steps)

        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        restore_ckpt = self.resume_from_checkpoint
        last_error: Optional[BaseException] = None
        history: List[Dict[str, Any]] = []

        try:
            while True:
                outcome = self._run_attempt(run_name, manager, restore_ckpt,
                                            experiment_path, coordinator)
                history.extend(outcome["history"])
                if outcome["status"] == "finished":
                    return Result(
                        metrics=outcome["last_metrics"],
                        checkpoint=(manager.latest_checkpoint()
                                    or self._coordinator_checkpoint(
                                        coordinator, from_memory=False)),
                        path=experiment_path,
                        # Surfaces e.g. "every async save failed": training
                        # succeeded but the run has no usable checkpoint.
                        error=outcome["error"],
                        metrics_history=history,
                    )
                last_error = outcome["error"]
                failures += 1
                exhausted = max_failures >= 0 and failures > max_failures
                # "fatal" = retrying cannot help (e.g. infeasible resources):
                # return even under max_failures=-1 instead of spinning forever.
                if exhausted or outcome["status"] == "fatal":
                    return Result(
                        metrics=outcome["last_metrics"],
                        checkpoint=(manager.latest_checkpoint()
                                    or self._coordinator_checkpoint(
                                        coordinator, from_memory=False)),
                        path=experiment_path,
                        error=last_error,
                        metrics_history=history,
                    )
                time.sleep(min(2.0 ** min(failures, 5) * 0.1, 5.0))  # restart backoff
                # Elastic restart from the latest checkpoint (ref: v2
                # controller RESTARTING state).  The coordinator's committed
                # step wins — its replica tier restores without re-reading
                # storage; the legacy manager path is the fallback.
                restore_ckpt = (self._coordinator_checkpoint(coordinator)
                                or manager.latest_checkpoint()
                                or self.resume_from_checkpoint)
        finally:
            if coordinator is not None:
                try:
                    ray_tpu.kill(coordinator)
                except Exception:
                    pass

    # ------------------------------------------------ coordinator restore
    def _coordinator_checkpoint(self, coordinator,
                                from_memory: bool = True) -> Optional[Checkpoint]:
        """Checkpoint handle for the coordinator's latest committed step.

        Prefers the in-memory replica tier (full shard set resident):
        payloads are materialized into a fresh local committed dir, so the
        handle's to_pytree() never touches the original storage — the
        Gemini-style fast recovery path."""
        if coordinator is None:
            return None
        try:
            src = ray_tpu.get(coordinator.restore_source.remote(), timeout=30)
        except Exception:
            return None
        if src is None:
            return None
        if from_memory and src.get("replicas"):
            try:
                from ray_tpu.checkpoint import materialize_from_payloads

                refs = src["replicas"]["refs"]
                payloads = {int(sid): ray_tpu.get(w["ref"])
                            for sid, w in refs.items()}
                local_root = tempfile.mkdtemp(prefix="ray_tpu_ckpt_mem_")
                path = materialize_from_payloads(local_root, src["step"],
                                                 payloads)
                from ray_tpu.checkpoint import metrics as _ckpt_metrics

                _ckpt_metrics.RESTORES.inc(tags={"source": "memory"})
                return Checkpoint(path)
            except Exception:
                pass  # fall back to the committed dir on storage
        return Checkpoint(src["path"])

    # ---------------------------------------------------------- one attempt
    def _run_attempt(self, run_name: str, manager: CheckpointManager,
                     restore_ckpt: Optional[Checkpoint], experiment_path: str,
                     coordinator=None) -> Dict:
        scfg = self.scaling_config
        world = scfg.num_workers
        DataParallelTrainer._collective_counter += 1
        group_name = f"train-{run_name}-{DataParallelTrainer._collective_counter}"

        # Gang-schedule the worker group via a placement group
        # (ref: backend_executor.py placement group per worker group).
        bundles = [scfg.worker_resources() for _ in range(world)]
        # Infeasible-by-construction requests fail immediately, not after the
        # reservation timeout.
        from ray_tpu._private.runtime import get_runtime
        from ray_tpu._private.scheduling import res_fits

        nodes = get_runtime().scheduler.nodes()
        for bundle in bundles:
            if not any(res_fits(n.total, bundle) for n in nodes if n.alive):
                return {"status": "fatal", "last_metrics": None, "history": [],
                        "error": RuntimeError(
                            f"Worker bundle {bundle} fits no node in the cluster "
                            f"(total: {ray_tpu.cluster_resources()})")}
        pg = placement_group(bundles, strategy=scfg.placement_strategy)
        try:
            if not pg.wait(timeout_seconds=60):
                total = ray_tpu.cluster_resources()
                return {"status": "failed", "last_metrics": None, "history": [],
                        "error": RuntimeError(
                            f"Could not reserve {world}x{scfg.worker_resources()} "
                            f"for the worker group within 60s (cluster: {total}). "
                            f"Reduce num_workers/resources_per_worker or add nodes.")}
            return self._run_with_pg(pg, run_name, group_name, manager,
                                     restore_ckpt, coordinator)
        finally:
            collective.destroy_collective_group(group_name)
            remove_placement_group(pg)

    def _worker_mode(self, pg) -> str:
        """threads (one TPU host, shared JAX client) vs processes (one
        jax.distributed rank per worker process — required once the worker
        group spans nodes: a thread here cannot execute on another host)."""
        mode = getattr(self.scaling_config, "worker_mode", "auto")
        if mode in ("threads", "processes"):
            return mode
        if mode != "auto":
            raise ValueError(f"worker_mode must be auto|threads|processes, got {mode!r}")
        from ray_tpu._private.runtime import get_runtime

        head = str(get_runtime().head_node_id)
        return "processes" if any(
            n is not None and n != head for n in pg.bundle_node_ids()
        ) else "threads"

    def _run_with_pg(self, pg, run_name: str, group_name: str,
                     manager: CheckpointManager, restore_ckpt,
                     coordinator=None) -> Dict:
        if self._worker_mode(pg) == "processes":
            # Process-tier workers ship checkpoints by value through the
            # report queue; the async sharded path is thread-tier only.
            return self._run_distributed(pg, run_name, group_name, manager,
                                         restore_ckpt)
        scfg = self.scaling_config
        world = scfg.num_workers
        dataset_shards = self._split_datasets(world)
        writers: List = []
        epoch = 0
        start_step = 0
        if coordinator is not None:
            from ray_tpu.checkpoint import ShardWriter

            # New attempt = new epoch: shards from a crashed attempt's
            # in-flight saves can no longer mix into this attempt's steps.
            epoch = ray_tpu.get(coordinator.new_epoch.remote(), timeout=30)
            latest = ray_tpu.get(coordinator.latest_committed.remote(),
                                 timeout=30)
            start_step = (latest + 1) if latest is not None else 0
            writers = [ShardWriter(coordinator, shard_id=rank,
                                   world_size=world, epoch=epoch)
                       for rank in range(world)]
        sessions: List[TrainSession] = []
        workers = []
        for rank in range(world):
            ctx = TrainContext(world_rank=rank, world_size=world, local_rank=rank,
                               trial_name=run_name, experiment_name=run_name,
                               group_name=group_name)
            session = TrainSession(ctx, checkpoint_to_restore=restore_ckpt,
                                   dataset_shards=dataset_shards[rank],
                                   shard_writer=writers[rank] if writers else None,
                                   start_step=start_step)
            sessions.append(session)
            workers.append(
                TrainWorker.options(
                    resources=scfg.worker_resources(),
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=rank),
                ).remote(rank, world, group_name)
            )

        refs = [
            w.run.remote(self.train_loop, self.train_loop_config, s)
            for w, s in zip(workers, sessions)
        ]

        history: List[Dict[str, Any]] = []
        last_metrics: Optional[Dict[str, Any]] = None
        pending = list(refs)
        try:
            while pending:
                ready, pending = ray_tpu.wait(pending, num_returns=len(pending), timeout=0.05)
                last_metrics, new_rows = self._drain_sessions(sessions, manager, last_metrics)
                history.extend(new_rows)
                for r in ready:
                    ray_tpu.get(r)  # raise worker errors here
            # Final drain after workers exit.
            last_metrics, new_rows = self._drain_sessions(sessions, manager, last_metrics)
            history.extend(new_rows)
            # Async saves still persisting in the background belong to this
            # run: let them land (and commit) before declaring it finished.
            for wtr in writers:
                try:
                    wtr.drain(timeout=120)
                except Exception:
                    pass
                wtr.close()
            return {"status": "finished", "last_metrics": last_metrics,
                    "history": history,
                    "error": self._check_async_saves(sessions, coordinator)}
        except (TaskError, RayTpuError) as e:  # worker failed
            for s in sessions:
                s.stop_requested.set()
            # Wake any worker blocked in a collective rendezvous NOW (the
            # group destroy in the caller's finally would also do it, but
            # draining results first needs them unwedged).
            try:
                collective.get_collective_group(group_name).destroy()
            except ValueError:
                pass
            for w in workers:
                ray_tpu.kill(w)
            # Queued-but-unstarted async saves die with the attempt (their
            # epoch is stale anyway); an in-flight persist may still commit,
            # which is always safe — the step is fully written.
            for wtr in writers:
                wtr.close()
            # Keep results reported before the crash (checkpoints especially —
            # the restart resumes from the last one registered).
            last_metrics, new_rows = self._drain_sessions(sessions, manager, last_metrics)
            history.extend(new_rows)
            return {"status": "failed", "last_metrics": last_metrics,
                    "history": history, "error": e}

    # ------------------------------------------------- multi-host attempt
    def _run_distributed(self, pg, run_name: str, group_name: str,
                         manager: CheckpointManager, restore_ckpt) -> Dict:
        """One attempt with process-tier workers spanning worker nodes.

        rank 0 reserves the jax.distributed coordinator on its own host,
        every worker joins with its placement-group rank, and the group's
        collectives become global SPMD programs (ref: backend_executor.py
        _setup_worker_group + torch/config.py:115 — the same
        coordinator-address + rank/world bootstrap, NCCL swapped for XLA)."""
        from ray_tpu.train.checkpoint import pack_checkpoint, unpack_checkpoint
        from ray_tpu.util.queue import Empty, Queue

        scfg = self.scaling_config
        world = scfg.num_workers
        if self.datasets:
            return {"status": "fatal", "last_metrics": None, "history": [],
                    "error": ValueError(
                        "datasets= require thread-tier workers (streaming "
                        "iterators cannot cross process boundaries); use "
                        "ScalingConfig(worker_mode='threads') or load data "
                        "inside the train_loop")}
        node_ids = pg.bundle_node_ids()
        node_order: List[Optional[str]] = []
        for n in node_ids:
            if n not in node_order:
                node_order.append(n)
        local_counter: Dict[Optional[str], int] = {}
        workers = []
        contexts: List[TrainContext] = []
        for rank in range(world):
            n = node_ids[rank] if rank < len(node_ids) else None
            local_rank = local_counter.get(n, 0)
            local_counter[n] = local_rank + 1
            contexts.append(TrainContext(
                world_rank=rank, world_size=world, local_rank=local_rank,
                node_rank=node_order.index(n), trial_name=run_name,
                experiment_name=run_name, group_name=group_name))
            workers.append(
                JaxDistTrainWorker.options(
                    isolation="process",
                    resources=scfg.worker_resources(),
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=rank),
                ).remote(rank, world, group_name))

        report_queue = Queue()
        history: List[Dict[str, Any]] = []
        last_metrics: Optional[Dict[str, Any]] = None

        def drain() -> None:
            nonlocal last_metrics
            while True:
                try:
                    item = report_queue.get_nowait()
                except Empty:
                    return
                if item.get("checkpoint_blob"):
                    # unpack lands in a ray_tpu_ckpt_ tempdir, which
                    # register() MOVES into managed storage (no double copy).
                    manager.register(unpack_checkpoint(item["checkpoint_blob"]),
                                     item["metrics"])
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    history.append(item["metrics"])

        try:
            coord = ray_tpu.get(workers[0].reserve_coordinator.remote(),
                                timeout=120)
            ray_tpu.get([w.setup.remote(coord) for w in workers], timeout=300)
            blob = pack_checkpoint(restore_ckpt)
            refs = [w.run.remote(self.train_loop, self.train_loop_config, ctx,
                                 report_queue, blob)
                    for w, ctx in zip(workers, contexts)]
            _drive_worker_refs(refs, drain)
            for w in workers:
                try:
                    ray_tpu.get(w.teardown.remote(), timeout=15)
                except Exception:
                    pass
            return {"status": "finished", "last_metrics": last_metrics,
                    "history": history, "error": None}
        except (TaskError, RayTpuError) as e:
            # A dead node/worker leaves the others wedged inside a global
            # SPMD collective; killing their processes (finally below) is
            # what unblocks the restart.
            drain()
            return {"status": "failed", "last_metrics": last_metrics,
                    "history": history, "error": e}
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            try:
                report_queue.shutdown()
            except Exception:
                pass

    def _check_async_saves(self, sessions: List[TrainSession],
                           coordinator) -> Optional[BaseException]:
        """Async saves fail out-of-band (drain deliberately swallows them so
        a later commit can supersede); a run where NO save ever committed
        must not finish silently with checkpoint=None and no error."""
        reported = sum(getattr(s, "async_saves_reported", 0) for s in sessions)
        if not reported or coordinator is None:
            return None
        from ray_tpu.checkpoint.writer import _invoke

        try:
            latest = _invoke(coordinator, "latest_committed")
        except Exception:
            return None
        if latest is not None:
            return None
        causes = []
        for s in sessions:
            handle = getattr(s, "last_save_handle", None)
            if handle is None:
                continue
            try:
                exc = handle.exception(timeout=0)
            except Exception:
                exc = None
            if exc is not None:
                causes.append(repr(exc))
        import logging

        err = RuntimeError(
            f"{reported} async checkpoint save(s) were reported but no step "
            "ever committed — the run finished without a usable checkpoint"
            + (f"; last shard errors: {causes}" if causes else ""))
        logging.getLogger(__name__).warning("%s", err)
        return err

    def _drain_sessions(self, sessions: List[TrainSession], manager: CheckpointManager,
                        last_metrics: Optional[Dict[str, Any]]):
        history = []
        for session in sessions:
            while True:
                try:
                    item = session.results.get_nowait()
                except queue.Empty:
                    break
                # Metrics history follows rank 0 (the reference's convention),
                # but checkpoints from ANY rank are registered — a loop where a
                # non-zero rank carries the checkpoint must not lose progress.
                if item["checkpoint"] is not None:
                    manager.register(item["checkpoint"], item["metrics"])
                if item["rank"] == 0:
                    last_metrics = item["metrics"]
                    history.append(item["metrics"])
        return last_metrics, history

    def _split_datasets(self, world: int) -> List[Dict[str, Any]]:
        """Per-rank dataset shards (ref: StreamSplitDataIterator coordinated
        split for Train ingest, data/_internal/iterator/stream_split_iterator.py:31)."""
        shards: List[Dict[str, Any]] = [{} for _ in range(world)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                its = ds.streaming_split(world)
                for rank in range(world):
                    shards[rank][name] = its[rank]
            else:
                for rank in range(world):
                    shards[rank][name] = ds
        return shards


class JaxTrainer(DataParallelTrainer):
    """The TPU trainer (BASELINE north star: `JaxTrainer` pinning workers to
    TPU processes).  Identical controller; workers join the 'xla' collective
    group so `ray_tpu.collective.allreduce` inside the loop compiles to psum
    over ICI, and `use_tpu=True` reserves chips per worker.

    Single host, the workers are threads sharing one JAX client (mesh mode).
    When the placement group lands workers on OTHER nodes (or
    ``ScalingConfig(worker_mode="processes")``), each worker becomes its own
    OS process joined into one jax.distributed cluster: jax.devices() spans
    every worker's chips, meshes ride ICI within a host and DCN across, and
    the same train_loop runs unchanged (multi-controller SPMD)."""
