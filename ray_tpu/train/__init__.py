"""ray_tpu.train — distributed training library (ref: python/ray/train)."""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager, load_pytree, save_pytree
from ray_tpu.train.config import (
    CheckpointConfig,
    DatasetConfig,
    ElasticConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.elastic import ElasticDatasetShard, SampleLedger
from ray_tpu.train.profiler import StepProfiler, active_profiler
from ray_tpu.train.profiler import configure as configure_profiler
from ray_tpu.train.session import (
    get_checkpoint,
    get_context,
    get_dataset_config,
    get_dataset_shard,
    report,
)
from ray_tpu.train.torch_trainer import TorchTrainer
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, Result

__all__ = [
    "Checkpoint", "CheckpointManager", "CheckpointConfig", "DataParallelTrainer",
    "DatasetConfig", "ElasticConfig", "ElasticDatasetShard", "FailureConfig", "JaxTrainer",
    "Result", "RunConfig", "SampleLedger", "ScalingConfig", "StepProfiler",
    "active_profiler", "configure_profiler",
    "get_checkpoint", "get_context", "get_dataset_config",
    "get_dataset_shard", "load_pytree",
    "report", "save_pytree", "TorchTrainer",
]
