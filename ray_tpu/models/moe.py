"""Mixture-of-Experts transformer with native expert parallelism.

The reference has NO native MoE/expert parallelism (SURVEY §2.3 — EP only via
integrated frameworks on Ray-provided process groups).  Here it is native and
TPU-shaped, the GShard recipe: top-k token-choice routing with a fixed expert
capacity, dispatch/combine expressed as einsums against a one-hot dispatch
tensor — everything is dense, static-shaped, and MXU-friendly, and when the
leading expert axis of the expert weights is sharded over the `expert` mesh
axis XLA lowers the dispatch einsums to all_to_all over ICI.  No
data-dependent shapes anywhere: over-capacity tokens are dropped (their
combine weight is zero), exactly as in GShard/Switch.

Reuses the GPT-2 attention block (models/gpt2.py); only the MLP is replaced
by the MoE layer.  An auxiliary load-balance loss (Switch §2.2 form:
E * sum_e f_e * p_e) keeps routing uniform.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import gpt2
from ray_tpu.models.gpt2 import _attention, _layernorm


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32768
    n_layer: int = 8
    n_head: int = 8
    d_model: int = 512
    seq_len: int = 1024
    n_experts: int = 8
    expert_mlp: int = 1024  # per-expert hidden width
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token capacity C, padded to a multiple of 8 for tiling."""
        c = int(math.ceil(self.capacity_factor * self.top_k * n_tokens
                          / self.n_experts))
        return max(8, -(-c // 8) * 8)

    @staticmethod
    def tiny() -> "MoEConfig":
        return MoEConfig(vocab_size=1024, n_layer=2, n_head=4, d_model=128,
                         seq_len=64, n_experts=4, expert_mlp=256)

    def _attn_view(self) -> gpt2.GPTConfig:
        """GPTConfig view so the attention kernel selection is shared."""
        return gpt2.GPTConfig(
            vocab_size=self.vocab_size, n_layer=self.n_layer,
            n_head=self.n_head, d_model=self.d_model, seq_len=self.seq_len,
            dtype=self.dtype, attn_impl=self.attn_impl)


def init_params(config: MoEConfig, key) -> Dict[str, Any]:
    D, L, V, S = config.d_model, config.n_layer, config.vocab_size, config.seq_len
    E, F = config.n_experts, config.expert_mlp
    std = 0.02
    resid_std = std / math.sqrt(2 * L)
    ks = jax.random.split(key, 8)

    def norm(key, shape, s):
        return jax.random.normal(key, shape, jnp.float32) * s

    return {
        "wte": norm(ks[0], (V, D), std),
        "wpe": norm(ks[1], (S, D), std / 2),
        "blocks": {
            "ln1_scale": jnp.ones((L, D)),
            "ln1_bias": jnp.zeros((L, D)),
            "qkv_w": norm(ks[2], (L, D, 3 * D), std),
            "qkv_b": jnp.zeros((L, 3 * D)),
            "out_w": norm(ks[3], (L, D, D), resid_std),
            "out_b": jnp.zeros((L, D)),
            "ln2_scale": jnp.ones((L, D)),
            "ln2_bias": jnp.zeros((L, D)),
            "router_w": norm(ks[4], (L, D, E), std),
            "expert_in_w": norm(ks[5], (L, E, D, F), std),
            "expert_in_b": jnp.zeros((L, E, F)),
            "expert_out_w": norm(ks[6], (L, E, F, D), resid_std),
            "expert_out_b": jnp.zeros((L, E, D)),
        },
        "lnf_scale": jnp.ones((D,)),
        "lnf_bias": jnp.zeros((D,)),
    }


def logical_axes(config: MoEConfig) -> Dict[str, Any]:
    La = "layers"
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_scale": (La, "norm"),
            "ln1_bias": (La, "norm"),
            "qkv_w": (La, "embed", "heads"),
            "qkv_b": (La, "heads"),
            "out_w": (La, "heads", "embed"),
            "out_b": (La, "norm"),
            "ln2_scale": (La, "norm"),
            "ln2_bias": (La, "norm"),
            "router_w": (La, "embed", None),
            "expert_in_w": (La, "expert", "embed", "mlp"),
            "expert_in_b": (La, "expert", "mlp"),
            "expert_out_w": (La, "expert", "mlp", "embed"),
            "expert_out_b": (La, "expert", "norm"),
        },
        "lnf_scale": ("norm",),
        "lnf_bias": ("norm",),
    }


def num_params(config: MoEConfig) -> int:
    D, L, V, S = config.d_model, config.n_layer, config.vocab_size, config.seq_len
    E, F = config.n_experts, config.expert_mlp
    attn = 4 * D + 3 * D * D + 3 * D + D * D + D
    moe = D * E + E * D * F + E * F + E * F * D + E * D
    return V * D + S * D + L * (attn + moe) + 2 * D


def _route(x32, router_w, config: MoEConfig):
    """Top-k token-choice routing.  x32: (N, D) fp32 tokens.

    Returns (dispatch (N, E, C) one-hot*bool, combine (N, E, C) weights,
    aux load-balance loss).  All static shapes.
    """
    N = x32.shape[0]
    E, K = config.n_experts, config.top_k
    C = config.capacity(N)

    logits = x32 @ router_w  # (N, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # Switch-style aux loss: E * sum_e (token fraction)_e * (mean prob)_e,
    # computed on the top-1 assignment.
    top1 = jnp.argmax(probs, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(top1, E, dtype=jnp.float32), axis=0)
    aux = E * jnp.sum(frac * jnp.mean(probs, axis=0))

    dispatch = jnp.zeros((N, E, C), jnp.float32)
    combine = jnp.zeros((N, E, C), jnp.float32)
    # Running per-expert fill count; carried across the k selections so the
    # 2nd choice lands after all 1st choices of the same expert.
    fill = jnp.zeros((E,), jnp.int32)
    masked = probs
    for _ in range(K):
        choice = jnp.argmax(masked, axis=-1)              # (N,)
        gate = jnp.take_along_axis(masked, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)   # (N, E)
        # Position of each token within its chosen expert's buffer.
        pos_in = jnp.cumsum(onehot, axis=0) - onehot + fill[None, :]
        pos = jnp.sum(pos_in * onehot, axis=-1)           # (N,)
        keep = pos < C
        oh_pos = jax.nn.one_hot(pos, C, dtype=jnp.float32) * keep[:, None]
        d = onehot.astype(jnp.float32)[:, :, None] * oh_pos[:, None, :]
        dispatch = dispatch + d
        combine = combine + gate[:, None, None] * d
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        masked = masked * (1.0 - onehot.astype(probs.dtype))
    # Renormalize combine weights over the kept choices per token.
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux


def _moe_mlp(x, blk, config: MoEConfig):
    """MoE feed-forward.  x: (B, S, D) -> (B, S, D), plus aux loss."""
    B, S, D = x.shape
    dt = config.dtype
    x32 = x.reshape(B * S, D).astype(jnp.float32)
    dispatch, combine, aux = _route(x32, blk["router_w"], config)

    # Dispatch: (N,E,C) x (N,D) -> (E,C,D); sharded over `expert` this is the
    # all_to_all that sends tokens to their expert's devices.
    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(dt), x.reshape(B * S, D))
    h = jnp.einsum("ecd,edf->ecf", xe, blk["expert_in_w"].astype(dt))
    h = jax.nn.gelu(h + blk["expert_in_b"].astype(dt)[:, None, :])
    ye = jnp.einsum("ecf,efd->ecd", h, blk["expert_out_w"].astype(dt))
    ye = ye + blk["expert_out_b"].astype(dt)[:, None, :]
    y = jnp.einsum("nec,ecd->nd", combine.astype(dt), ye)
    return y.reshape(B, S, D), aux


def _block(x, blk, config: MoEConfig):
    B, S, D = x.shape
    H, hd = config.n_head, config.head_dim
    dt = config.dtype

    h = _layernorm(x, blk["ln1_scale"], blk["ln1_bias"]).astype(dt)
    qkv = h @ blk["qkv_w"].astype(dt) + blk["qkv_b"].astype(dt)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = _attention(q.reshape(B, S, H, hd), k.reshape(B, S, H, hd),
                      v.reshape(B, S, H, hd), config._attn_view())
    x = x + attn.reshape(B, S, D) @ blk["out_w"].astype(dt) + blk["out_b"].astype(dt)

    h = _layernorm(x, blk["ln2_scale"], blk["ln2_bias"]).astype(dt)
    y, aux = _moe_mlp(h, blk, config)
    return x + y, aux


def forward(params: Dict[str, Any], tokens, config: MoEConfig):
    """tokens (B, S) int32 -> (logits (B, S, V) fp32, total aux loss)."""
    B, S = tokens.shape
    dt = config.dtype
    x = params["wte"][tokens].astype(dt) + params["wpe"][:S].astype(dt)

    block_fn = partial(_block, config=config)
    if config.remat:
        block_fn = jax.checkpoint(block_fn)

    def scan_body(carry, blk):
        x, aux = block_fn(carry, blk)
        return x, aux

    x, auxes = lax.scan(scan_body, x, params["blocks"])
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"]).astype(dt)
    logits = jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(dt),
                        preferred_element_type=jnp.float32)
    return logits, jnp.sum(auxes)


def loss_fn(params, tokens, targets, config: MoEConfig):
    logits, aux = forward(params, tokens, config)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt) + config.aux_loss_weight * aux


def make_train_step(config: MoEConfig, optimizer):
    def step(params, opt_state, tokens, targets):
        import optax

        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
