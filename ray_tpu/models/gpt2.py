"""GPT-2 — the flagship model, pure-JAX and mesh-native.

Counterpart of the reference's GPT-2 DDP train benchmark (BASELINE config 4;
ref harness python/ray/train/examples + release/train_tests), redesigned for
TPU: parameters are a plain pytree with *logical axis* annotations
(parallel/mesh.py) so one model definition runs under any dp/fsdp/tp/sp mesh;
blocks are stacked and scanned (`lax.scan`) for O(1) compile depth;
per-block rematerialization (`jax.checkpoint`) trades FLOPs for HBM; matmuls
run in bfloat16 on the MXU with fp32 layernorm/softmax/loss.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # 50257 padded to a multiple of 128 for the MXU
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    seq_len: int = 1024
    dtype: Any = jnp.bfloat16
    remat: bool = True
    #: "save_attn" saves flash-attention outputs across the remat boundary —
    #: measured best on v5e (recomputing attention in bwd is the one thing
    #: worth HBM); "full" rematerializes everything.
    remat_policy: str = "save_attn"
    attn_impl: str = "auto"  # auto | xla | pallas | splash | ring | ulysses
    #: Pipeline stages over the mesh's `pipe` axis (parallel/pipeline.py);
    #: 1 = no pipelining. n_layer % pp_stages must be 0.
    pp_stages: int = 1
    #: GPipe microbatches; 0 = pp_stages (minimum). Must divide batch.
    pp_microbatches: int = 0
    #: Sequence-chunked LM-head loss: compute logits + cross-entropy in
    #: seq chunks of this size under jax.checkpoint, so the fp32 (B, S, V)
    #: logits tensor (3.3 GB for GPT-2-small at B=16) never hits HBM in
    #: either pass.  0 = single unchunked einsum.
    loss_chunk: int = 0
    #: LM-head loss implementation: "auto" flips to the fused pallas CE
    #: kernel (ops/fused_ce.py — logits never in HBM) when its roofline
    #: cost model predicts a win (small d_model / large-vocab regime;
    #: D=768 stays on the dense/chunked path), "fused"/"dense" force it.
    loss_impl: str = "auto"
    #: Dtype the (B, S, V) logits MATERIALIZE in.  bf16 halves the step's
    #: single biggest HBM tensor (fwd logits + bwd dlogits, ~1.6 GB each at
    #: B=16 fp32) for ~+1 MFU point on v5e; the loss reductions (logsumexp /
    #: target gather) still accumulate in fp32 so training is stable — only
    #: per-logit rounding changes (measured init-loss delta 0.01).  Set to
    #: jnp.float32 for exact-softmax parity.
    logits_dtype: Any = jnp.bfloat16
    #: lax.scan unroll factor over the stacked layers: >1 widens XLA's
    #: scheduling window so HBM-bound elementwise ops overlap matmuls
    #: across layer boundaries.
    scan_unroll: int = 1
    #: Splash-attention kernel tile sizes.
    attn_block_q: int = 512
    attn_block_kv: int = 512
    #: With remat_policy="attn_outside": also save the (B, S, 4D) MLP
    #: activation across the post-block checkpoint, skipping the mlp_in
    #: matmul's backward recompute for ~1.2 GB of activations (B=16).
    save_mlp_act: bool = False
    #: False = fully unroll the layer loop (a python loop, O(n_layer)
    #: compile depth) instead of lax.scan, for ANY remat policy (ignored
    #: when pp_stages > 1 — the pipeline schedule owns the layer loop).
    #: Removes the scan's dynamic-update-slice residual stacking
    #: (~10 ms/step in the r3 trace) at the cost of a longer first
    #: compile (~33 s vs ~15 s for GPT-2-small).
    scan_layers: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @staticmethod
    def small() -> "GPTConfig":
        return GPTConfig()  # 124M

    @staticmethod
    def tiny() -> "GPTConfig":
        return GPTConfig(vocab_size=1024, n_layer=2, n_head=4, d_model=128, seq_len=128)


def init_params(config: GPTConfig, key) -> Dict[str, Any]:
    """Plain pytree; blocks stacked on a leading layer axis for lax.scan."""
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    D, L, V, S = config.d_model, config.n_layer, config.vocab_size, config.seq_len
    std = 0.02
    resid_std = std / math.sqrt(2 * L)

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s)

    ks = jax.random.split(k_blocks, 6)
    return {
        "wte": norm(k_wte, (V, D), std),
        "wpe": norm(k_wpe, (S, D), std / 2),
        "blocks": {
            "ln1_scale": jnp.ones((L, D)),
            "ln1_bias": jnp.zeros((L, D)),
            "qkv_w": norm(ks[0], (L, D, 3 * D), std),
            "qkv_b": jnp.zeros((L, 3 * D)),
            "out_w": norm(ks[1], (L, D, D), resid_std),
            "out_b": jnp.zeros((L, D)),
            "ln2_scale": jnp.ones((L, D)),
            "ln2_bias": jnp.zeros((L, D)),
            "mlp_in_w": norm(ks[2], (L, D, 4 * D), std),
            "mlp_in_b": jnp.zeros((L, 4 * D)),
            "mlp_out_w": norm(ks[3], (L, 4 * D, D), resid_std),
            "mlp_out_b": jnp.zeros((L, D)),
        },
        "lnf_scale": jnp.ones((D,)),
        "lnf_bias": jnp.zeros((D,)),
    }


def logical_axes(config: GPTConfig) -> Dict[str, Any]:
    """Logical-axis pytree matching init_params.  The leading stacked-layer
    axis is "layers": sharded over `pipe` when pipelining (each stage holds
    its contiguous slice of layers), unsharded otherwise (pipe=1)."""
    L = "layers"
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_scale": (L, "norm"),
            "ln1_bias": (L, "norm"),
            "qkv_w": (L, "embed", "heads"),
            "qkv_b": (L, "heads"),
            "out_w": (L, "heads", "embed"),
            "out_b": (L, "norm"),
            "ln2_scale": (L, "norm"),
            "ln2_bias": (L, "norm"),
            "mlp_in_w": (L, "embed", "mlp"),
            "mlp_in_b": (L, "mlp"),
            "mlp_out_w": (L, "mlp", "embed"),
            "mlp_out_b": (L, "norm"),
        },
        "lnf_scale": ("norm",),
        "lnf_bias": ("norm",),
    }


def num_params(config: GPTConfig) -> int:
    D, L, V, S = config.d_model, config.n_layer, config.vocab_size, config.seq_len
    per_block = 4 * D + 3 * D * D + 3 * D + D * D + D + 8 * D * D + 4 * D + D
    return V * D + S * D + L * per_block + 2 * D


def flops_per_token(config: GPTConfig) -> float:
    """6*P (fwd+bwd matmul) + attention score/value FLOPs (PaLM appendix B)."""
    return 6.0 * num_params(config) + 12.0 * config.n_layer * config.d_model * config.seq_len


def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * lax.rsqrt(var + eps) * scale + bias
    return out


def _attention(q, k, v, config: GPTConfig):
    """Causal multi-head attention.  q,k,v: (B, S, H, hd).

    "ring"/"ulysses" are the context-parallel paths (ops/ring_attention.py):
    attention runs seq-sharded over the mesh's `seq` axis — callers install
    the mesh via jax.set_mesh (parallel/train_state.py jit_train_step(mesh=)).
    """
    impl = config.attn_impl
    if impl not in ("auto", "xla", "pallas", "splash", "ring", "ulysses"):
        raise ValueError(
            f"Unknown attn_impl: {impl!r} "
            "(use auto|xla|pallas|splash|ring|ulysses)")
    if impl == "splash" or (impl == "auto" and jax.default_backend() == "tpu"):
        try:
            from ray_tpu.ops.attention import splash_attention

            return splash_attention(q, k, v, causal=True,
                                    block_q=config.attn_block_q,
                                    block_kv=config.attn_block_kv)
        except Exception as e:  # noqa: BLE001 — fall through to flash/xla
            if impl == "splash":
                raise
            import warnings

            warnings.warn(f"splash attention unavailable ({e}); falling back")
    if impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, causal=True)
    if impl == "ulysses":
        from ray_tpu.ops.ring_attention import ulysses_attention

        return ulysses_attention(q, k, v, causal=True)
    if impl == "pallas" or (impl == "auto" and jax.default_backend() == "tpu"):
        try:
            from ray_tpu.ops.attention import flash_attention

            return flash_attention(q, k, v, causal=True)
        except ImportError as e:
            if impl == "pallas":
                raise
            import warnings

            warnings.warn(f"flash attention unavailable ({e}); using XLA path")
    # XLA path: einsum softmax einsum; fp32 softmax.
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    S = q.shape[1]
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_pre_attn(x, blk, config: GPTConfig):
    """ln1 + qkv projection (the part BEFORE attention)."""
    from jax.ad_checkpoint import checkpoint_name

    dt = config.dtype
    h = _layernorm(x, blk["ln1_scale"], blk["ln1_bias"]).astype(dt)
    h = checkpoint_name(h, "ln1_out")
    qkv = h @ blk["qkv_w"].astype(dt) + blk["qkv_b"].astype(dt)
    return checkpoint_name(qkv, "qkv")


def _block_post_attn(x, attn, blk, config: GPTConfig):
    """Residual out-projection + MLP (the part AFTER attention)."""
    from jax.ad_checkpoint import checkpoint_name

    dt = config.dtype
    x = x + attn @ blk["out_w"].astype(dt) + blk["out_b"].astype(dt)
    h = _layernorm(x, blk["ln2_scale"], blk["ln2_bias"]).astype(dt)
    h = checkpoint_name(h, "ln2_out")
    h = jax.nn.gelu(h @ blk["mlp_in_w"].astype(dt) + blk["mlp_in_b"].astype(dt))
    h = checkpoint_name(h, "mlp_act")
    return x + h @ blk["mlp_out_w"].astype(dt) + blk["mlp_out_b"].astype(dt)


def _block(x, blk, config: GPTConfig):
    """One transformer block (pre-attn half + attention + post-attn half);
    x: (B, S, D) in compute dtype."""
    from jax.ad_checkpoint import checkpoint_name

    B, S, D = x.shape
    H, hd = config.n_head, config.head_dim

    qkv = _block_pre_attn(x, blk, config)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    attn = _attention(q.reshape(B, S, H, hd), k.reshape(B, S, H, hd),
                      v.reshape(B, S, H, hd), config).reshape(B, S, D)
    attn = checkpoint_name(attn, "attn_out")
    return _block_post_attn(x, attn, blk, config)


def forward_hidden(params: Dict[str, Any], tokens, config: GPTConfig):
    """tokens (B, S) int32 -> final-layernormed hidden states (B, S, D)."""
    B, S = tokens.shape
    dt = config.dtype
    x = params["wte"][tokens].astype(dt) + params["wpe"][:S].astype(dt)

    block_fn = partial(_block, config=config)
    if config.save_mlp_act and config.remat_policy != "attn_outside":
        raise ValueError(
            "save_mlp_act applies only to remat_policy='attn_outside' "
            "(use remat_policy='save_attn_mlp' with the scan path)")
    if config.remat and config.remat_policy == "attn_outside":
        # Attention OUTSIDE the remat regions: profiling (PERF.md r3 trace)
        # showed save_attn still re-ran the splash FORWARD in the backward
        # — saving the attention output does not save the kernel's own
        # custom-vjp residuals (lse), so the recompute regenerated them
        # (~10.8 ms/step).  Splitting the block into two checkpointed
        # halves with attention between them lets jax save q,k,v + lse
        # (~1.2 GB at B=16) and skip the re-forward entirely.
        #
        # Only sound with flash-style attention kernels whose custom-vjp
        # residuals are VMEM-scale: the plain XLA path would instead save
        # the full (B, H, S, S) probs per layer for the backward (~5 GB
        # at the benchmark shape).  "auto" resolves to splash on TPU; on
        # CPU (tests) the shapes are tiny, so the XLA-path saves are fine.
        if config.attn_impl == "xla":
            raise ValueError(
                "remat_policy='attn_outside' with attn_impl='xla' would "
                "materialize per-layer (B, H, S, S) probs as saved "
                "residuals; use a flash-style attn_impl or save_attn")
        pre = jax.checkpoint(partial(_block_pre_attn, config=config))
        post_policy = (
            jax.checkpoint_policies.save_only_these_names("mlp_act")
            if config.save_mlp_act else None)
        post = (jax.checkpoint(partial(_block_post_attn, config=config),
                               policy=post_policy)
                if post_policy is not None
                else jax.checkpoint(partial(_block_post_attn, config=config)))
        H, hd = config.n_head, config.head_dim

        def split_body(carry, blk):
            x0 = carry
            qkv = pre(x0, blk)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            Bq, Sq = q.shape[0], q.shape[1]
            attn = _attention(
                q.reshape(Bq, Sq, H, hd), k.reshape(Bq, Sq, H, hd),
                v.reshape(Bq, Sq, H, hd), config).reshape(Bq, Sq, -1)
            return post(x0, attn, blk), None

        if config.pp_stages > 1:
            raise ValueError(
                "remat_policy='attn_outside' does not compose with "
                "pp_stages>1 yet; use save_attn")
        if config.scan_layers:
            x, _ = lax.scan(split_body, x, params["blocks"],
                            unroll=config.scan_unroll)
        else:
            for i in range(config.n_layer):
                blk_i = jax.tree_util.tree_map(lambda a: a[i],
                                               params["blocks"])
                x, _ = split_body(x, blk_i)
        x = _layernorm(x, params["lnf_scale"], params["lnf_bias"]).astype(dt)
        return x
    if config.remat:
        policies = {
            "save_attn": lambda: jax.checkpoint_policies.save_only_these_names(
                "attn_out"),
            # Intermediate points on the recompute-vs-HBM curve: also save
            # the qkv projection and/or the mlp activation, skipping their
            # matmuls' recompute in the backward at ~0.9/1.2 GB of saved
            # activations (B=16).  Measured on v5e r3 — see PERF.md.
            "save_attn_qkv": lambda: jax.checkpoint_policies.save_only_these_names(
                "attn_out", "qkv"),
            "save_attn_mlp": lambda: jax.checkpoint_policies.save_only_these_names(
                "attn_out", "mlp_act"),
            "save_attn_qkv_mlp": lambda: jax.checkpoint_policies.save_only_these_names(
                "attn_out", "qkv", "mlp_act"),
            # Save every matmul input/output across the boundary: bwd then
            # recomputes only elementwise ops (layernorm/gelu/adds).  ~3 GB
            # of saved activations at B=16 — the compiler-friendly stand-in
            # for remat=False (which crashes the TPU compiler helper).
            "save_matmuls": lambda: jax.checkpoint_policies.save_only_these_names(
                "ln1_out", "qkv", "attn_out", "ln2_out", "mlp_act"),
            "dots": lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "everything": lambda: jax.checkpoint_policies.everything_saveable,
            "full": lambda: None,
        }
        if config.remat_policy not in policies:
            raise ValueError(
                f"unknown remat_policy {config.remat_policy!r} "
                f"(use {sorted(policies) + ['attn_outside']})")
        policy = policies[config.remat_policy]()
        block_fn = (jax.checkpoint(block_fn, policy=policy) if policy is not None
                    else jax.checkpoint(block_fn))

    def scan_body(carry, blk):
        return block_fn(carry, blk), None

    if not config.scan_layers and config.pp_stages == 1:
        # Unrolled layer loop for any remat policy (see scan_layers doc).
        for i in range(config.n_layer):
            blk_i = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            x, _ = scan_body(x, blk_i)
        x = _layernorm(x, params["lnf_scale"], params["lnf_bias"]).astype(dt)
        return x

    if config.pp_stages > 1:
        # GPipe over the `pipe` mesh axis: each stage scans its local slice
        # of the stacked blocks (leading "layers" axis is pipe-sharded).
        from ray_tpu.parallel.pipeline import pipeline_apply

        if config.n_layer % config.pp_stages:
            raise ValueError(
                f"n_layer {config.n_layer} % pp_stages {config.pp_stages} != 0")
        # The mesh is authoritative for the stage count: a mismatched config
        # would silently run a different schedule than requested.
        from ray_tpu._private.jax_compat import get_abstract_mesh

        amesh = get_abstract_mesh()
        if amesh is not None and "pipe" in getattr(amesh, "shape", {}) \
                and amesh.shape["pipe"] not in (1, config.pp_stages):
            raise ValueError(
                f"config.pp_stages={config.pp_stages} but mesh pipe axis is "
                f"{amesh.shape['pipe']}")

        def stage_fn(local_blocks, h):
            h, _ = lax.scan(scan_body, h, local_blocks)
            return h

        x = pipeline_apply(
            stage_fn, params["blocks"], x,
            n_microbatches=config.pp_microbatches or config.pp_stages)
    else:
        x, _ = lax.scan(scan_body, x, params["blocks"],
                        unroll=config.scan_unroll)
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"]).astype(dt)
    return x


def forward(params: Dict[str, Any], tokens, config: GPTConfig):
    """tokens (B, S) int32 -> logits (B, S, V) fp32."""
    x = forward_hidden(params, tokens, config)
    # Tied LM head; logits accumulate in fp32 for a stable loss.
    return jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(config.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params, tokens, targets, config: GPTConfig):
    x = forward_hidden(params, tokens, config)
    wte = params["wte"].astype(config.dtype)
    B, S, D = x.shape
    C = config.loss_chunk
    impl = config.loss_impl
    if impl not in ("auto", "fused", "dense"):
        raise ValueError(f"loss_impl must be auto|fused|dense, got {impl!r}")
    if impl == "auto":
        from ray_tpu.ops.fused_ce import fused_ce_wins

        # TPU-only flip (same gating as attn_impl): the roofline constants
        # are v5e's, and interpret-mode pallas off-TPU would be a silent
        # orders-of-magnitude slowdown.
        import jax as _jax

        impl = "fused" if (_jax.default_backend() == "tpu" and fused_ce_wins(
            D, jnp.dtype(config.logits_dtype).itemsize)) else "dense"
    if impl == "fused":
        from ray_tpu.ops.fused_ce import fused_lm_head_ce

        return fused_lm_head_ce(x, wte, targets)
    if not C or C >= S:
        logits = jnp.einsum("bsd,vd->bsv", x, wte,
                            preferred_element_type=config.logits_dtype)
        # lse - target_logit (not log_softmax) keeps the (B,S,V) traffic
        # to one reduction pass — measured ~2 MFU points on v5e.  The
        # reductions upcast to fp32 regardless of the materialized dtype.
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt_logit = jnp.take_along_axis(
            logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
        return jnp.mean(lse - tgt_logit)

    # Chunked head: per-chunk logits live only in VMEM-scale tiles; bwd
    # recomputes them under jax.checkpoint, so peak HBM holds (B, C, V)
    # instead of (B, S, V) in both passes.
    if S % C:
        raise ValueError(f"loss_chunk {C} must divide seq_len {S}")
    n = S // C
    xs = x.reshape(B, n, C, D).swapaxes(0, 1)      # (n, B, C, D)
    ts = targets.reshape(B, n, C).swapaxes(0, 1)   # (n, B, C)

    @jax.checkpoint
    def chunk_loss(x_c, t_c):
        logits = jnp.einsum("bsd,vd->bsv", x_c, wte,
                            preferred_element_type=config.logits_dtype)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(
            logits, t_c[..., None], axis=-1)[..., 0].astype(jnp.float32)
        return jnp.sum(lse - tgt)

    def body(acc, xt):
        return acc + chunk_loss(*xt), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
    return total / (B * S)


def make_optimizer(learning_rate=3e-4, weight_decay=0.1, b1=0.9, b2=0.95,
                   grad_clip=1.0, mu_dtype=None):
    """AdamW with the first moment stored in bf16 by default: the momentum
    is noise-tolerant (unlike nu, which stays fp32) and halving its HBM
    read+write is worth ~+0.8 MFU on v5e (r5 sweep: 47.5 -> 48.2; 13-step
    loss 9.562 vs 9.565).  Pass mu_dtype=jnp.float32 for exact parity."""
    import optax

    if mu_dtype is None:
        mu_dtype = jnp.bfloat16
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def make_train_step(config: GPTConfig, optimizer):
    """Pure (params, opt_state, tokens, targets) -> (params, opt_state, loss).

    Under jit with sharded inputs this is the whole distributed step: XLA
    derives the gradient psum/reduce-scatter from the shardings — there is no
    hand-written gradient sync (the DDP allreduce of the reference's
    _TorchBackend lives inside the compiled program here).
    """

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets, config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def make_eval_step(config: GPTConfig):
    def step(params, tokens, targets):
        return loss_fn(params, tokens, targets, config)

    return step
