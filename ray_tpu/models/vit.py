"""ViT — Vision Transformer for image classification, TPU-first.

Same functional contract as the other model families (gpt2.py/llama.py):
``init_params / logical_axes / forward / loss_fn / make_train_step``.
(Ref capability: the reference's vision training/serving examples run
torchvision models through Train/Serve — e.g. doc/source/train torch
image examples; here the vision family is a native JAX ViT, Dosovitskiy
et al. 2020.)

TPU notes: patch embedding is ONE big matmul (patches are unfolded
host-free with reshape/transpose — no convolution layout surprises on the
MXU), everything runs in ``config.dtype`` (bf16 by default) with fp32
layernorms/softmax and an fp32 classifier head, and the logical axes
("embed"/"heads"/"mlp") shard exactly like the language models so the
same mesh rules apply.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layer: int = 12
    n_head: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    #: lax.scan over the stacked layer axis (O(1) compile depth); False
    #: unrolls — the same trade the language models expose (gpt2
    #: scan_layers: unrolled can win runtime at the cost of compile time).
    scan_layers: bool = True

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return 3 * self.patch_size * self.patch_size

    @classmethod
    def tiny(cls) -> "ViTConfig":
        return cls(image_size=32, patch_size=8, num_classes=10,
                   d_model=64, n_layer=2, n_head=4, d_ff=128)

    @classmethod
    def base(cls) -> "ViTConfig":
        return cls()  # ViT-B/16


def init_params(config: ViTConfig, key) -> Dict[str, Any]:
    D, L, F, H = config.d_model, config.n_layer, config.d_ff, config.n_head
    P, C = config.patch_dim, config.num_classes
    N = config.n_patches
    k = iter(jax.random.split(key, 8))

    def dense(key, shape, scale=0.02):
        return (jax.random.normal(key, shape) * scale).astype(jnp.float32)

    blocks = {
        "ln1_scale": jnp.ones((L, D)), "ln1_bias": jnp.zeros((L, D)),
        "wqkv": dense(next(k), (L, D, 3 * D)),
        "wo": dense(next(k), (L, D, D)),
        "ln2_scale": jnp.ones((L, D)), "ln2_bias": jnp.zeros((L, D)),
        "w_up": dense(next(k), (L, D, F)), "b_up": jnp.zeros((L, F)),
        "w_down": dense(next(k), (L, F, D)), "b_down": jnp.zeros((L, D)),
    }
    return {
        "patch_embed": dense(next(k), (P, D)),
        "patch_bias": jnp.zeros((D,)),
        "pos_embed": dense(next(k), (N + 1, D)),
        "cls_token": dense(next(k), (1, D)),
        "blocks": blocks,
        "lnf_scale": jnp.ones((D,)), "lnf_bias": jnp.zeros((D,)),
        "head": dense(next(k), (D, C)), "head_bias": jnp.zeros((C,)),
    }


def logical_axes(config: ViTConfig) -> Dict[str, Any]:
    L = "layers"
    return {
        "patch_embed": ("patch", "embed"),
        "patch_bias": ("embed",),
        "pos_embed": ("seq_pos", "embed"),
        "cls_token": (None, "embed"),
        "blocks": {
            "ln1_scale": (L, "norm"), "ln1_bias": (L, "norm"),
            "wqkv": (L, "embed", "heads"),
            "wo": (L, "heads", "embed"),
            "ln2_scale": (L, "norm"), "ln2_bias": (L, "norm"),
            "w_up": (L, "embed", "mlp"), "b_up": (L, "mlp"),
            "w_down": (L, "mlp", "embed"), "b_down": (L, "norm"),
        },
        "lnf_scale": ("norm",), "lnf_bias": ("norm",),
        "head": ("embed", "vocab"), "head_bias": ("vocab",),
    }


def num_params(config: ViTConfig) -> int:
    D, L, F = config.d_model, config.n_layer, config.d_ff
    per_block = 4 * D + 3 * D * D + D * D + D * F + F + F * D + D
    return (config.patch_dim * D + D + (config.n_patches + 1) * D + D
            + L * per_block + 2 * D + D * config.num_classes
            + config.num_classes)


def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(
        x.dtype)


def patchify(images, config: ViTConfig):
    """(B, H, W, 3) -> (B, N, patch_dim) with pure reshape/transpose — the
    patch embed then runs as one (B*N, patch_dim) @ (patch_dim, D) matmul
    on the MXU (no conv layout pass needed)."""
    B = images.shape[0]
    p = config.patch_size
    g = config.image_size // p
    x = images.reshape(B, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, g, g, p, p, 3)
    return x.reshape(B, g * g, p * p * 3)


def _block(x, blk, config: ViTConfig):
    B, T, D = x.shape
    H = config.n_head
    h = _layernorm(x, blk["ln1_scale"], blk["ln1_bias"])
    qkv = h @ blk["wqkv"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)
    scores = (q @ k.transpose(0, 1, 3, 2)).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(D // H))
    attn = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = (attn @ v).transpose(0, 2, 1, 3).reshape(B, T, D)
    x = x + out @ blk["wo"].astype(x.dtype)
    h = _layernorm(x, blk["ln2_scale"], blk["ln2_bias"])
    h = jax.nn.gelu(h @ blk["w_up"].astype(x.dtype)
                    + blk["b_up"].astype(x.dtype))
    return x + h @ blk["w_down"].astype(x.dtype) \
        + blk["b_down"].astype(x.dtype)


def forward(params: Dict[str, Any], images, config: ViTConfig):
    """(B, H, W, 3) images -> (B, num_classes) logits (fp32)."""
    x = patchify(images.astype(config.dtype), config)
    x = x @ params["patch_embed"].astype(config.dtype) \
        + params["patch_bias"].astype(config.dtype)
    B = x.shape[0]
    cls = jnp.broadcast_to(params["cls_token"].astype(config.dtype),
                           (B, 1, config.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(config.dtype)
    if config.scan_layers:
        # Stacked block params scan on their leading layer axis: one traced
        # block body regardless of depth.
        def body(h, blk):
            return _block(h, blk, config), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
    else:
        for i in range(config.n_layer):
            blk = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            x = _block(x, blk, config)
    x = _layernorm(x, params["lnf_scale"], params["lnf_bias"])
    cls_out = x[:, 0].astype(jnp.float32)
    return cls_out @ params["head"] + params["head_bias"]


def loss_fn(params, images, labels, config: ViTConfig):
    logits = forward(params, images, config)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)
    return nll.mean()


def accuracy(params, images, labels, config: ViTConfig):
    logits = forward(params, images, config)
    return (jnp.argmax(logits, axis=-1) == labels).mean()


def make_optimizer(learning_rate=3e-4, weight_decay=0.05, b1=0.9, b2=0.999):
    import optax

    return optax.adamw(learning_rate, b1=b1, b2=b2,
                       weight_decay=weight_decay)


def make_train_step(config: ViTConfig, optimizer):
    """Same contract as gpt2.make_train_step: XLA derives all gradient
    collectives from the shardings."""
    import optax

    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels,
                                                  config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
