"""Small MLP classifier — the fashion-MNIST workload (BASELINE config 1;
ref harness: python/ray/train/examples/pytorch/)."""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp


def init_params(key, sizes: Tuple[int, ...] = (784, 128, 64, 10)) -> List[Dict[str, Any]]:
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, d_in, d_out in zip(keys, sizes[:-1], sizes[1:]):
        params.append({
            "w": jax.random.normal(k, (d_in, d_out)) * (2.0 / d_in) ** 0.5,
            "b": jnp.zeros((d_out,)),
        })
    return params


def forward(params, x):
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    out = params[-1]
    return x @ out["w"] + out["b"]


def loss_fn(params, x, y):
    logits = forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))


def accuracy(params, x, y):
    return jnp.mean(jnp.argmax(forward(params, x), axis=-1) == y)
