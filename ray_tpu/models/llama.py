"""Llama-family decoder: RMSNorm + RoPE + SwiGLU + grouped-query attention.

Second dense model family on the same parallel substrate as GPT-2 (the
reference is a runtime, not a model zoo — these models exist to prove the
framework's training path on the architectures users actually run).  The
module mirrors ``models/gpt2.py``'s functional contract exactly —
init_params / logical_axes / forward / loss_fn / make_train_step — so every
mesh axis (data/fsdp/tensor/seq via logical-axis rules, ring/ulysses
attention for long context) composes without model-specific glue.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import gpt2 as _g


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 8
    n_head: int = 8
    #: grouped-query attention: kv heads < query heads share k/v
    n_kv_head: int = 4
    d_model: int = 512
    #: SwiGLU hidden dim (Llama uses ~8/3 * d_model rounded to 256)
    d_ff: int = 1408
    seq_len: int = 1024
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    attn_impl: str = "auto"  # auto | xla | pallas | splash | ring | ulysses
    attn_block_q: int = 512
    attn_block_kv: int = 512
    logits_dtype: Any = jnp.bfloat16
    # kept for MeshSpec probe parity with GPTConfig (pipelining of the llama
    # stack rides the same `layers` axis; GPipe wiring arrives with demand)
    pp_stages: int = 1
    pp_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def q_per_kv(self) -> int:
        return self.n_head // self.n_kv_head

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig(vocab_size=1024, n_layer=2, n_head=4, n_kv_head=2,
                           d_model=128, d_ff=384, seq_len=128)

    def __post_init__(self):
        assert self.d_model % self.n_head == 0
        assert self.n_head % self.n_kv_head == 0


def init_params(config: LlamaConfig, key) -> Dict[str, Any]:
    """Plain pytree; blocks stacked on a leading layer axis for lax.scan."""
    D, L, V = config.d_model, config.n_layer, config.vocab_size
    H, KV, hd, F = config.n_head, config.n_kv_head, config.head_dim, config.d_ff
    std = 0.02
    resid_std = std / math.sqrt(2 * L)
    k_wte, k_blocks, k_head = jax.random.split(key, 3)

    def norm(key, shape, s):
        return jax.random.normal(key, shape, jnp.float32) * s

    ks = jax.random.split(k_blocks, 7)
    return {
        "wte": norm(k_wte, (V, D), std),
        "blocks": {
            "attn_norm": jnp.ones((L, D)),
            "wq": norm(ks[0], (L, D, H * hd), std),
            "wk": norm(ks[1], (L, D, KV * hd), std),
            "wv": norm(ks[2], (L, D, KV * hd), std),
            "wo": norm(ks[3], (L, H * hd, D), resid_std),
            "mlp_norm": jnp.ones((L, D)),
            "w_gate": norm(ks[4], (L, D, F), std),
            "w_up": norm(ks[5], (L, D, F), std),
            "w_down": norm(ks[6], (L, F, D), resid_std),
        },
        "final_norm": jnp.ones((D,)),
        # Untied LM head (Llama convention; GPT-2 ties to wte).
        "lm_head": norm(k_head, (V, D), std),
    }


def logical_axes(config: LlamaConfig) -> Dict[str, Any]:
    L = "layers"
    return {
        "wte": ("vocab", "embed"),
        "blocks": {
            "attn_norm": (L, "norm"),
            "wq": (L, "embed", "heads"),
            "wk": (L, "embed", "heads"),
            "wv": (L, "embed", "heads"),
            "wo": (L, "heads", "embed"),
            "mlp_norm": (L, "norm"),
            "w_gate": (L, "embed", "mlp"),
            "w_up": (L, "embed", "mlp"),
            "w_down": (L, "mlp", "embed"),
        },
        "final_norm": ("norm",),
        "lm_head": ("vocab", "embed"),
    }


def num_params(config: LlamaConfig) -> int:
    D, L, V, F = (config.d_model, config.n_layer, config.vocab_size,
                  config.d_ff)
    hd = config.head_dim
    attn = D * config.n_head * hd + 2 * D * config.n_kv_head * hd \
        + config.n_head * hd * D
    mlp = 3 * D * F
    per_block = 2 * D + attn + mlp
    return 2 * V * D + L * per_block + D


def flops_per_token(config: LlamaConfig) -> float:
    return 6.0 * num_params(config) \
        + 12.0 * config.n_layer * config.d_model * config.seq_len


def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return x32 * lax.rsqrt(ms + eps) * scale


def _rope(x, theta: float):
    """Rotary position embedding over (B, S, H, hd) — rotate-half form."""
    B, S, H, hd = x.shape
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = jnp.arange(S, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]  # (1, S, 1, half)
    sin = jnp.sin(angles)[None, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def _block(x, blk, config: LlamaConfig):
    dt = config.dtype
    B, S, D = x.shape
    H, KV, hd = config.n_head, config.n_kv_head, config.head_dim

    h = _rmsnorm(x, blk["attn_norm"], config.rms_eps).astype(dt)
    q = (h @ blk["wq"].astype(dt)).reshape(B, S, H, hd)
    k = (h @ blk["wk"].astype(dt)).reshape(B, S, KV, hd)
    v = (h @ blk["wv"].astype(dt)).reshape(B, S, KV, hd)
    q = _rope(q, config.rope_theta)
    k = _rope(k, config.rope_theta)
    if KV != H:
        # GQA: each kv head serves q_per_kv query heads.
        k = jnp.repeat(k, config.q_per_kv, axis=2)
        v = jnp.repeat(v, config.q_per_kv, axis=2)
    # Reuse the GPT-2 attention dispatcher (xla/pallas/splash/ring/ulysses):
    # it only reads attn_impl/blocks/head-shape from the config.
    attn = _g._attention(q, k, v, config).astype(dt).reshape(B, S, H * hd)
    x = x + attn @ blk["wo"].astype(dt)

    h = _rmsnorm(x, blk["mlp_norm"], config.rms_eps).astype(dt)
    gate = jax.nn.silu((h @ blk["w_gate"].astype(dt)).astype(jnp.float32))
    up = (h @ blk["w_up"].astype(dt)).astype(jnp.float32)
    x = x + ((gate * up).astype(dt) @ blk["w_down"].astype(dt))
    return x


def forward_hidden(params: Dict[str, Any], tokens, config: LlamaConfig):
    dt = config.dtype
    x = params["wte"][tokens].astype(dt)

    def layer(x, blk):
        out = _block(x, blk, config)
        return out, None

    if config.remat:
        layer = jax.checkpoint(layer)
    x, _ = lax.scan(layer, x, params["blocks"])
    return _rmsnorm(x, params["final_norm"], config.rms_eps).astype(dt)


def forward(params: Dict[str, Any], tokens, config: LlamaConfig):
    x = forward_hidden(params, tokens, config)
    return jnp.einsum("bsd,vd->bsv", x, params["lm_head"].astype(config.dtype),
                      preferred_element_type=jnp.float32)


def loss_fn(params, tokens, targets, config: LlamaConfig):
    x = forward_hidden(params, tokens, config)
    logits = jnp.einsum("bsd,vd->bsv", x,
                        params["lm_head"].astype(config.dtype),
                        preferred_element_type=config.logits_dtype)
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return jnp.mean(lse - tgt)


def make_optimizer(learning_rate=3e-4, weight_decay=0.1, b1=0.9, b2=0.95,
                   grad_clip=1.0):
    return _g.make_optimizer(learning_rate=learning_rate,
                             weight_decay=weight_decay, b1=b1, b2=b2,
                             grad_clip=grad_clip)


def make_train_step(config: LlamaConfig, optimizer):
    """Same contract as gpt2.make_train_step: XLA derives all gradient
    collectives from the shardings."""
    import optax

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
