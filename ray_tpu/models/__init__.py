from ray_tpu.models import gpt2, llama, moe, vit

__all__ = ["gpt2", "llama", "moe", "vit"]
