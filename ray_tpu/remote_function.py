"""@ray_tpu.remote functions (ref: python/ray/remote_function.py:41).

``RemoteFunction._remote`` resolves options, builds a TaskSpec and submits it
to the runtime (ref: remote_function.py:303 → _raylet.pyx:3688 submit_task).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.ids import TaskID
from ray_tpu._private.option_utils import resolve_task_options
from ray_tpu._private.runtime import current_task_context, get_runtime
from ray_tpu._private.task_spec import TaskSpec


class RemoteFunction:
    def __init__(self, func: Callable, default_options: Optional[Dict[str, Any]] = None):
        if inspect.isclass(func):
            raise TypeError("Use @remote on classes via ActorClass (actor.py)")
        self._function = func
        self._default_options = default_options or {}
        # Options are static per RemoteFunction instance (options() returns a
        # new one) — resolved once per config generation, not per .remote()
        # call (task hot path).  Lazy, NOT at decoration time: module-level
        # @remote runs before init() applies _system_config overrides, and
        # resolve_task_options reads GLOBAL_CONFIG defaults.
        self._resolved_opts = None
        self._resolved_gen = -1
        # Static per function — probing inspect flags on every .remote()
        # call costs ~10µs each at task-storm rates.
        self._is_generator_fn = inspect.isgeneratorfunction(func)
        self.__name__ = getattr(func, "__name__", "remote_function")
        self.__doc__ = getattr(func, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use {self.__name__}.remote()."
        )

    def options(self, **options) -> "RemoteFunction":
        merged = dict(self._default_options)
        merged.update(options)
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        from ray_tpu._private.config import GLOBAL_CONFIG

        if self._resolved_gen != GLOBAL_CONFIG.generation:
            self._resolved_opts = resolve_task_options(
                self._default_options, is_actor=False)
            self._resolved_gen = GLOBAL_CONFIG.generation
        return self._remote_resolved(args, kwargs, self._resolved_opts)

    def _remote(self, args, kwargs, **options):
        return self._remote_resolved(
            args, kwargs, resolve_task_options(options, is_actor=False))

    def _remote_resolved(self, args, kwargs, opts):
        runtime = get_runtime()
        parent = current_task_context()
        generator = self._is_generator_fn or opts["num_returns"] in (
            "dynamic",
            "streaming",
        )
        num_returns = opts["num_returns"]
        if not isinstance(num_returns, int):
            num_returns = 1
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            name=opts.get("name") or self.__name__,
            func=self._function,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources=opts["resources"],
            strategy=opts["scheduling_strategy"],
            max_retries=opts["max_retries"],
            retry_exceptions=opts["retry_exceptions"],
            isolation=opts["isolation"],
            generator=generator,
            parent_task_id=parent.task_id if parent else None,
            runtime_env=opts.get("runtime_env"),
        )
        return runtime.submit_task(spec)

    def bind(self, *args, **kwargs):
        """DAG-building entry point (ref: dag/dag_node.py); returns a lazy node."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)
