"""CheckpointCoordinator: sharded two-phase commit + replica registry.

The coordinator is the single writer of *commit* state.  Shard writers
(writer.py) run phase 1 — each persists its shard under the step's
``.tmp`` directory and reports ``shard_complete`` — and the coordinator
runs phase 2 when the last shard lands: global manifest + ``COMMIT``
marker + atomic rename (layout.commit_step_dir).  A save whose writer
dies mid-flight simply never completes its shard set; the ``.tmp`` dir is
swept on the next save and restore only ever sees committed steps.

It doubles as the registry for the in-memory replica tier (Gemini, SOSP
'23): writers put their host snapshots into the object store and register
the refs here; the last ``replica_steps`` committed steps stay resident,
optionally mirrored into a ReplicaHolder actor on a *different* node so
one node loss cannot take out both the workers and their fast-restore
copies.

Run it as an actor (``ray_tpu.remote(CheckpointCoordinator).remote(...)``)
for multi-worker training, or instantiate it directly for single-process
use — the writer handles both transparently.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import fault_injection
from ray_tpu.checkpoint import layout
from ray_tpu.checkpoint import metrics as ckpt_metrics
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


class CheckpointCoordinator:
    def __init__(self, root: str, keep: Optional[int] = None,
                 replica_steps: int = 2, replicate_to_peer: bool = True):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep = keep
        self.replica_steps = max(0, int(replica_steps))
        self.replicate_to_peer = replicate_to_peer
        self._lock = threading.RLock()
        #: step -> {"num_shards", "epoch", "done": {shard: manifest}, "t0"}
        self._pending: Dict[int, Dict[str, Any]] = {}  # guarded_by: _lock
        #: (step, epoch) pairs whose save aborted: a sibling shard arriving
        #: after the abort must not resurrect the pending entry.
        self._aborted: set = set()  # guarded_by: _lock
        #: steps whose phase-2 commit is in flight (pending entry already
        #: removed, rename not yet done): the stale-tmp sweep and
        #: shard_failed must treat their .tmp dirs as live.
        self._committing: set = set()  # guarded_by: _lock
        # Restart-safe: rebuild committed state from disk (the same scan
        # CheckpointManager does) so a driver restart resumes seamlessly.
        self._committed: List[int] = layout.list_committed_steps(self.root)  # guarded_by: _lock
        self._last_commit_time: Optional[float] = None  # guarded_by: _lock
        self._epoch = 0  # guarded_by: _lock
        #: step -> {shard_id: ObjectRef} (refs held here pin the objects)
        self._replicas: Dict[int, Dict[int, Any]] = {}  # guarded_by: _lock
        self._peer = None
        #: monotonic time before which no peer (re)start is attempted —
        #: inf disables peer replication, 0 means "try on next use".  A
        #: dead holder (its node preempted) schedules a RETRY instead of
        #: latching unavailable forever: elastic training outlives any
        #: one peer node.
        self._peer_retry_at = float("inf") if not replicate_to_peer else 0.0
        self._sweep_stale_tmp()

    # ------------------------------------------------------------ phase 1
    def new_epoch(self) -> int:
        """Called by the training controller at each attempt start: pending
        saves from a previous (crashed) attempt must never mix shards with
        the new one, so their epochs divorce them."""
        with self._lock:
            self._epoch += 1
            return self._epoch

    def begin_save(self, step: int, num_shards: int, epoch: int = 0) -> str:
        with self._lock:
            if step in self._committed or step in self._committing:
                raise ValueError(f"step {step} is already committed")
            if (step, epoch) in self._aborted:
                raise RuntimeError(
                    f"step {step} was aborted (a sibling shard failed)")
            pending = self._pending.get(step)
            tmp = layout.tmp_dir(self.root, step)
            if pending is not None and pending["epoch"] != epoch:
                # Stale attempt's half-written save: discard it wholesale.
                shutil.rmtree(tmp, ignore_errors=True)
                pending = None
            if pending is None:
                self._sweep_stale_tmp()
                shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp, exist_ok=True)
                self._pending[step] = {"num_shards": num_shards, "epoch": epoch,
                                       "done": {}, "t0": time.monotonic()}
            elif pending["num_shards"] != num_shards:
                raise ValueError(
                    f"step {step} began with num_shards={pending['num_shards']}, "
                    f"got {num_shards}")
            return tmp

    def shard_complete(self, step: int, shard_id: int, manifest: dict,
                       epoch: int = 0) -> bool:
        """Phase-1 completion for one shard; commits (phase 2) when it is
        the last one.  Returns True iff this call committed the step."""
        with self._lock:
            pending = self._pending.get(step)
            if pending is None or pending["epoch"] != epoch:
                return False  # stale writer from a torn-down attempt
            pending["done"][shard_id] = manifest
            if len(pending["done"]) < pending["num_shards"]:
                return False
            # Hand the step from _pending to _committing without a gap:
            # a concurrent begin_save's stale-tmp sweep must keep seeing
            # this step as owned, or it rmtrees the .tmp dir mid-commit.
            del self._pending[step]
            self._committing.add(step)
        try:
            self._commit(step, pending)
        finally:
            with self._lock:
                self._committing.discard(step)
        return True

    def shard_failed(self, step: int, shard_id: int, error: str = "",
                     epoch: int = 0) -> None:
        """Abort a pending save: the step can never commit with a missing
        shard, so drop it and reclaim the tmp dir."""
        with self._lock:
            if step in self._committing:
                # Every shard already landed and phase 2 owns the tmp dir;
                # a duplicate/stale failure report must not rmtree it.
                return
            pending = self._pending.get(step)
            if pending is not None and pending["epoch"] != epoch:
                return
            self._pending.pop(step, None)
            self._aborted.add((step, epoch))
            self._replicas.pop(step, None)
        shutil.rmtree(layout.tmp_dir(self.root, step), ignore_errors=True)
        ckpt_metrics.SAVE_FAILURES.inc(tags={"phase": "shard_write"})
        logger.warning("checkpoint step %s aborted (shard %s failed: %s)",
                       step, shard_id, error)

    # ------------------------------------------------------------ phase 2
    def _commit(self, step: int, pending: Dict[str, Any]) -> None:
        t0 = time.monotonic()
        try:
            with tracing.span("checkpoint.commit",
                              attributes={"step": step,
                                          "num_shards": pending["num_shards"]}):
                fault_injection.check("ckpt_commit")
                layout.commit_step_dir(self.root, step, pending["done"])
        except BaseException:
            ckpt_metrics.SAVE_FAILURES.inc(tags={"phase": "commit"})
            shutil.rmtree(layout.tmp_dir(self.root, step), ignore_errors=True)
            with self._lock:
                self._replicas.pop(step, None)
            raise
        now = time.time()
        with self._lock:
            self._committed.append(step)
            self._committed.sort()
            if self._last_commit_time is not None:
                ckpt_metrics.STALENESS_SECONDS.set(now - self._last_commit_time)
            self._last_commit_time = now
            # Aborted steps at or below the new latest can never be retried
            # (writers allocate monotonically increasing step ids), so the
            # poison set stays bounded in a long-lived coordinator.
            latest = self._committed[-1]
            self._aborted = {(s, e) for (s, e) in self._aborted if s > latest}
            self._apply_retention()
            self._trim_replicas()
        ckpt_metrics.COMMITS.inc()
        ckpt_metrics.COMMIT_SECONDS.observe(time.monotonic() - t0)

    def _apply_retention(self) -> None:  # requires_lock: _lock
        if self.keep is None or self.keep <= 0:
            return
        while len(self._committed) > self.keep:
            victim = self._committed.pop(0)
            shutil.rmtree(layout.final_dir(self.root, victim),
                          ignore_errors=True)
            self._replicas.pop(victim, None)

    def _sweep_stale_tmp(self) -> None:  # requires_lock: _lock
        """Reclaim ``.tmp`` dirs no live pending save owns (crashed saves
        from this or a previous process)."""
        for path in layout.list_stale_tmp_dirs(self.root):
            name = os.path.basename(path)
            step = layout.parse_step(name[: -len(layout.TMP_SUFFIX)])
            if step not in self._pending and step not in self._committing:
                shutil.rmtree(path, ignore_errors=True)

    # --------------------------------------------------------- inspection
    def latest_committed(self) -> Optional[int]:
        with self._lock:
            return self._committed[-1] if self._committed else None

    def committed_steps(self) -> List[int]:
        with self._lock:
            return list(self._committed)

    def committed_path(self, step: int) -> str:
        return layout.final_dir(self.root, step)

    def latest_committed_path(self) -> Optional[str]:
        step = self.latest_committed()
        return None if step is None else layout.final_dir(self.root, step)

    # ------------------------------------------------------- replica tier
    def put_replica(self, step: int, shard_id: int, wrapped_ref: dict) -> None:
        """Register one shard's in-memory snapshot (``{"ref": ObjectRef}``
        — nested so the actor call does not materialize it).  Holding the
        ref here pins the snapshot in the object store; when a peer node
        exists, the holder actor there keeps a second copy."""
        if self.replica_steps <= 0:
            return
        ref = wrapped_ref["ref"]
        with self._lock:
            self._replicas.setdefault(step, {})[shard_id] = ref
            self._trim_replicas()
        peer = self._ensure_peer()
        if peer is not None:
            try:
                peer.hold.remote(step, shard_id, {"ref": ref})
            except Exception:
                self._drop_peer()

    def _trim_replicas(self) -> None:  # requires_lock: _lock
        # Keep the last replica_steps *committed* steps plus anything still
        # pending (its commit may be in flight).
        keep = set(self._committed[-self.replica_steps:]) if self.replica_steps else set()
        keep |= set(self._pending)
        keep |= self._committing
        for step in [s for s in self._replicas if s not in keep]:
            del self._replicas[step]
        committed_resident = [s for s in self._replicas if s in set(self._committed)]
        ckpt_metrics.REPLICA_STEPS.set(len(committed_resident))
        peer = self._peer
        if peer is not None:
            try:
                peer.trim.remote(sorted(self._replicas))
            except Exception:
                pass

    def _drop_peer(self, retry_after_s: float = 5.0) -> None:
        """Forget a failed/dead peer and schedule a revival attempt."""
        self._peer = None
        if self._peer_retry_at != float("inf"):
            self._peer_retry_at = time.monotonic() + retry_after_s

    def _peer_alive(self) -> bool:
        """Best-effort liveness of the holder actor (fire-and-forget
        ``hold`` calls never surface a dead peer on their own)."""
        peer = self._peer
        if peer is None:
            return False
        try:
            from ray_tpu._private.runtime import get_runtime

            state = get_runtime().get_actor_state(peer._ray_actor_id)
        except Exception:
            return True  # cannot tell — assume alive
        return state is not None and state.state != "DEAD"

    def _ensure_peer(self):
        if self._peer is not None:
            if self._peer_alive():
                return self._peer
            # The holder's node was preempted out from under it: drop it
            # and fall through into the revival path immediately.
            self._drop_peer(retry_after_s=0.0)
        if time.monotonic() < self._peer_retry_at:
            return None
        try:
            from ray_tpu.checkpoint.replica import start_peer_holder

            self._peer = start_peer_holder()
        except Exception:
            self._peer = None
        if self._peer is None:
            # No peer node available right now (single-node cluster, or
            # capacity preempted away) — retry later, don't latch off.
            self._drop_peer(retry_after_s=15.0)
            return None
        self._mirror_to_peer(self._peer)
        return self._peer

    def _mirror_to_peer(self, peer) -> None:
        """Seed a fresh holder with every resident replica shard so a
        revived peer is immediately useful for recovery."""
        with self._lock:
            resident = [(step, sid, ref)
                        for step, shards in self._replicas.items()
                        for sid, ref in shards.items()]
        for step, sid, ref in resident:
            try:
                peer.hold.remote(step, sid, {"ref": ref})
            except Exception:
                self._drop_peer()
                return

    def peer_payloads(self, step: Optional[int] = None) -> Optional[dict]:
        """Fetch a full shard-payload set for ``step`` (default: latest
        committed) from the peer holder — the recovery tier that survives
        the WRITERS' node dying.  Bounded wait; None when there is no
        peer, it died, or it holds only a partial set (caller falls back
        to disk — never hangs)."""
        peer = self._peer
        if peer is None:
            return None
        if step is None:
            step = self.latest_committed()
        if step is None:
            return None
        try:
            import ray_tpu

            payloads = ray_tpu.get(peer.fetch.remote(step), timeout=20)
        except Exception:
            self._drop_peer()
            return None
        want = self._num_shards_of(step)
        if want is None or len(payloads) < want:
            return None
        return {"step": step, "payloads": payloads}

    def replica_refs(self, step: Optional[int] = None) -> Optional[dict]:
        """{"step", "refs": {shard_id: {"ref": ObjectRef}}} for the newest
        committed step with a full replica set (or the given step), else
        None.  Refs ride nested in dicts so neither the actor return nor a
        later call materializes them prematurely."""
        with self._lock:
            candidates = [step] if step is not None else list(reversed(self._committed))
            for s in candidates:
                refs = self._replicas.get(s)
                if not refs:
                    continue
                want = self._num_shards_of(s)
                if want is not None and len(refs) >= want:
                    return {"step": s,
                            "refs": {sid: {"ref": r} for sid, r in refs.items()}}
        return None

    def _num_shards_of(self, step: int) -> Optional[int]:
        path = os.path.join(layout.final_dir(self.root, step),
                            layout.GLOBAL_MANIFEST)
        try:
            import json

            with open(path) as f:
                return int(json.load(f)["num_shards"])
        except Exception:
            return None

    def restore_source(self) -> Optional[dict]:
        """What a restarting trainer should restore from: the latest
        committed step, preferring the in-memory replica tier."""
        step = self.latest_committed()
        if step is None:
            return None
        return {"step": step,
                "path": layout.final_dir(self.root, step),
                "replicas": self.replica_refs(step)}

    def stats(self) -> dict:
        with self._lock:
            return {
                "committed_steps": list(self._committed),
                "pending_steps": sorted(self._pending),
                "committing_steps": sorted(self._committing),
                "aborted_entries": len(self._aborted),
                "replica_steps": sorted(self._replicas),
                "epoch": self._epoch,
                "peer_replication": self._peer is not None,
            }
