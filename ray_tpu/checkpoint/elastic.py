"""Elastic restore: place restored host leaves onto a *different* mesh.

Shards on disk record the writer's world size, but assemble_tree already
reconciles that into full host arrays — so restoring into a new topology
is purely a placement problem: device_put every leaf with a sharding
derived from the new mesh.  The device placement goes through the
jax_compat shard round-trip (``jax_compat.reshard``) so old and new jax
spellings of NamedSharding/device_put both work.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


def default_pspec(leaf: np.ndarray, mesh) -> "Any":
    """Shard axis 0 over the mesh's first axis when it divides evenly;
    replicate otherwise — the mirror of layout.partition_for."""
    from jax.sharding import PartitionSpec

    axis_names = list(mesh.axis_names)
    if not axis_names:
        return PartitionSpec()
    first = axis_names[0]
    size = int(np.prod([mesh.shape[a] for a in (first,)]))
    if leaf.ndim >= 1 and size > 1 and leaf.shape[0] % size == 0:
        return PartitionSpec(first)
    return PartitionSpec()


def reshard_tree(host_tree: Any, mesh, pspec: Optional[Any] = None,
                 pspec_fn: Optional[Callable] = None) -> Any:
    """device_put every leaf of a host pytree onto ``mesh``.

    ``pspec`` — one PartitionSpec for every leaf (leaves it cannot apply
    to fall back to replication); ``pspec_fn(leaf, mesh) -> PartitionSpec``
    — per-leaf control; neither — ``default_pspec``.
    """
    import jax

    from ray_tpu._private import jax_compat

    def place(leaf):
        a = np.asarray(leaf)
        if pspec_fn is not None:
            spec = pspec_fn(a, mesh)
        elif pspec is not None:
            spec = pspec
        else:
            spec = default_pspec(a, mesh)
        try:
            return jax_compat.reshard(a, mesh, spec)
        except ValueError:
            # Spec does not divide this leaf (e.g. a scalar under a fixed
            # user pspec): replicate rather than fail the restore.
            from jax.sharding import PartitionSpec

            return jax_compat.reshard(a, mesh, PartitionSpec())

    return jax.tree.map(place, host_tree)
