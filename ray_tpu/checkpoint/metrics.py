"""Checkpoint subsystem metrics.

Declared at import time like the serve metrics modules so
``scripts/check_metrics.py`` can lint them; exported through the process
registry on ``/metrics`` via the metrics agent (util/metrics.py).

The anchor set mirrors what the Check-N-Run / Gemini papers measure:
how long the training step actually *blocks* for a save (the number async
checkpointing exists to shrink), how much the background tier writes,
commit latency, and recovery staleness (time between committed steps —
the worst-case recomputation window after a failure).
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge, Histogram

#: Seconds the training step was blocked by a save call (async: the
#: device->host snapshot only; sync: the full persist + commit).
SAVE_BLOCK_SECONDS = Histogram(
    "ray_tpu_ckpt_save_block_seconds",
    "Seconds the caller was blocked by a checkpoint save "
    "(async saves: device-to-host snapshot only)",
    tag_keys=("mode",),
)

#: End-to-end duration of one shard's persist (background thread).
SAVE_SECONDS = Histogram(
    "ray_tpu_ckpt_save_seconds",
    "End-to-end seconds for one shard's persist (snapshot excluded)",
)

BYTES_WRITTEN = Counter(
    "ray_tpu_ckpt_bytes_written_total",
    "Bytes of checkpoint shard data written to storage",
)

COMMITS = Counter(
    "ray_tpu_ckpt_commits_total",
    "Checkpoints committed (two-phase commit completed: all shards "
    "landed, COMMIT marker written, directory renamed into place)",
)

COMMIT_SECONDS = Histogram(
    "ray_tpu_ckpt_commit_seconds",
    "Seconds for the commit phase (global manifest + COMMIT marker + "
    "atomic rename)",
)

SAVE_FAILURES = Counter(
    "ray_tpu_ckpt_save_failures_total",
    "Checkpoint save/commit attempts that failed (aborted pending saves "
    "included); tagged with the failing phase",
    tag_keys=("phase",),
)

#: Gap between the two most recent commits — the recomputation window a
#: failure right now would cost (0 until the second commit).
STALENESS_SECONDS = Gauge(
    "ray_tpu_ckpt_staleness_seconds",
    "Seconds between the last two committed checkpoints (worst-case "
    "lost-work window on failure)",
)

RESTORES = Counter(
    "ray_tpu_ckpt_restores_total",
    "Checkpoint restores, tagged with the tier that served them",
    tag_keys=("source",),
)

RESTORE_SECONDS = Histogram(
    "ray_tpu_ckpt_restore_seconds",
    "Seconds to assemble a full pytree from a committed checkpoint",
    tag_keys=("source",),
)

REPLICA_STEPS = Gauge(
    "ray_tpu_ckpt_replica_steps",
    "Checkpoint steps currently held in the in-memory replica tier",
)
