"""ray_tpu.checkpoint — asynchronous distributed checkpointing.

The subsystem (see docs/checkpointing.md):

* ``ShardWriter.save_async``   — device->host snapshot on the step
  boundary, persist + commit on a background thread (Check-N-Run).
* ``CheckpointCoordinator``    — sharded two-phase commit: every shard
  lands under ``checkpoint_NNNNNN.tmp/`` with its manifest, then one
  atomic rename + ``COMMIT`` marker makes the step visible.
* in-memory replica tier       — last-k step snapshots pinned in the
  object store and mirrored to a peer node (Gemini) for fast recovery.
* ``restore_pytree`` / ``reshard_tree`` — restore from any committed
  step, elastically resharding onto a different mesh/world size.

Chaos fault points: ``ckpt_shard_write``, ``ckpt_commit``,
``ckpt_restore`` (ray_tpu._private.fault_injection).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ray_tpu.checkpoint import layout
from ray_tpu.checkpoint import metrics as ckpt_metrics
from ray_tpu.checkpoint.coordinator import CheckpointCoordinator
from ray_tpu.checkpoint.elastic import reshard_tree
from ray_tpu.checkpoint.layout import (
    is_committed_dir,
    latest_committed_step,
    list_committed_steps,
)
from ray_tpu.checkpoint.replica import ReplicaHolder
from ray_tpu.checkpoint.writer import SaveHandle, ShardWriter, snapshot_to_host

__all__ = [
    "CheckpointCoordinator", "ReplicaHolder", "SaveHandle", "ShardWriter",
    "is_committed_dir", "latest_committed_step", "list_committed_steps",
    "materialize_from_payloads", "reshard_tree", "restore_latest",
    "restore_pytree", "snapshot_to_host",
]


def restore_pytree(path: str, template: Optional[Any] = None, *,
                   mesh=None, pspec=None, pspec_fn=None,
                   _source: str = "disk") -> Any:
    """Restore the full pytree from one *committed* checkpoint directory.

    With ``mesh`` (and optionally ``pspec``/``pspec_fn``) the leaves are
    device_put with shardings for that mesh — the elastic-restore path; a
    mesh of any shape/world size works because the host assembly already
    reconciled the writer's sharding.  Without a mesh the leaves stay
    host numpy arrays.  ``template`` only validates structure.
    """
    from ray_tpu._private import fault_injection
    from ray_tpu.util import tracing

    t0 = time.monotonic()
    with tracing.span("checkpoint.restore",
                      attributes={"path": path, "source": _source}):
        fault_injection.check("ckpt_restore")
        if not layout.is_committed_dir(path):
            raise ValueError(
                f"{path} is not a committed checkpoint (missing COMMIT "
                "marker or non-final name) — refusing to restore a "
                "potentially torn directory")
        tree = layout.assemble_tree(path)
        if template is not None:
            _check_template(tree, template)
        if mesh is not None:
            tree = reshard_tree(tree, mesh, pspec=pspec, pspec_fn=pspec_fn)
    ckpt_metrics.RESTORES.inc(tags={"source": _source})
    ckpt_metrics.RESTORE_SECONDS.observe(time.monotonic() - t0,
                                         tags={"source": _source})
    return tree


def restore_latest(root: str, template: Optional[Any] = None, *,
                   mesh=None, pspec=None, pspec_fn=None) -> Optional[Any]:
    """Restore from the latest committed step under ``root`` (e.g. a serve
    deployment loading model weights); None when nothing is committed."""
    step = layout.latest_committed_step(root)
    if step is None:
        return None
    return restore_pytree(layout.final_dir(root, step), template,
                          mesh=mesh, pspec=pspec, pspec_fn=pspec_fn)


def materialize_from_payloads(root: str, step: int,
                              payloads: Dict[int, dict]) -> str:
    """Write a committed checkpoint dir from in-memory replica payloads
    (fast restore without touching the original storage); returns the
    committed path."""
    return layout.write_committed_from_payloads(root, step, payloads)


def _check_template(tree: Any, template: Any) -> None:
    import jax

    got = jax.tree.structure(tree)
    want = jax.tree.structure(template)
    if got != want:
        raise ValueError(
            f"restored pytree structure {got} does not match template {want}")
