"""Per-worker shard writer: async device->host snapshot + background persist.

Check-N-Run's decomposition (NSDI '22): the only work on the training
step's critical path is the device->host snapshot (a copy); serializing,
writing to storage, registering the in-memory replica and the two-phase
commit all happen on a dedicated background thread.  ``save_async``
returns a SaveHandle the moment the snapshot lands on host, and a serial
executor preserves step order per shard.

The writer talks to a CheckpointCoordinator that may be a plain local
object (single-process) or an actor handle (multi-worker) — ``_invoke``
papers over the difference.

Chaos: the persist path consults the ``ckpt_shard_write`` fault point; an
injected (or real) failure aborts the pending step at the coordinator so
the commit can never include a half-written shard.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu._private import fault_injection
from ray_tpu.checkpoint import layout
from ray_tpu.checkpoint import metrics as ckpt_metrics
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


def _profiler_record(bucket: str, start: float, end: float) -> None:
    """Attribute an interval to the train step profiler when one is active
    on this thread.  Probed via sys.modules — the checkpoint layer must not
    import the train package (trainer -> collective import chain), and if
    the profiler module was never imported, none can be active."""
    mod = sys.modules.get("ray_tpu.train.profiler")
    if mod is not None:
        mod.record(bucket, start, end)


def _telemetry():
    """Device-telemetry plane iff loaded (same probe idiom): the snapshot
    is a device->host transfer and the host copy stages bytes in the
    ``ckpt_staging`` pool until its persist releases them."""
    return sys.modules.get("ray_tpu.util.device_telemetry")


def _invoke(coordinator, method: str, *args):
    """Call a coordinator method whether it is local or an actor handle."""
    m = getattr(coordinator, method)
    remote = getattr(m, "remote", None)
    if remote is None:
        return m(*args)
    import ray_tpu

    return ray_tpu.get(remote(*args))


def snapshot_to_host(tree: Any) -> Any:
    """Device arrays -> host numpy (the only step-blocking work)."""
    import jax

    return jax.device_get(tree)


class SaveHandle:
    """Future-ish handle for one async save."""

    def __init__(self, future: Future, step: int, block_seconds: float):
        self._future = future
        self.step = step
        #: seconds the caller was blocked (snapshot time)
        self.block_seconds = block_seconds

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None) -> dict:
        """Waits for the persist; raises if the shard write failed."""
        return self._future.result(timeout)

    def exception(self, timeout: Optional[float] = None):
        return self._future.exception(timeout)


class ShardWriter:
    def __init__(self, coordinator, shard_id: int = 0, world_size: int = 1,
                 epoch: int = 0, replicate: bool = True):
        self.coordinator = coordinator
        self.shard_id = int(shard_id)
        self.world_size = int(world_size)
        self.epoch = int(epoch)
        self.replicate = replicate
        self._aborted = threading.Event()
        self._exec = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"ckpt-shard-{shard_id}")

    # ----------------------------------------------------------- save API
    def save_async(self, step: int, tree: Any) -> SaveHandle:
        """Snapshot now, persist in the background; blocks only for the
        device->host copy."""
        t0 = time.monotonic()
        w0 = time.time()
        with tracing.span("checkpoint.save",
                          attributes={"step": step, "shard": self.shard_id,
                                      "phase": "snapshot"}):
            host_tree = snapshot_to_host(tree)
        block = time.monotonic() - t0
        ckpt_metrics.SAVE_BLOCK_SECONDS.observe(block, tags={"mode": "async"})
        # The snapshot is the only save work blocking the training step —
        # attribute exactly it to the step profiler's ckpt_block bucket.
        _profiler_record("ckpt_block", w0, w0 + block)
        self._ledger_snapshot(host_tree, w0, w0 + block)
        future = self._exec.submit(self._persist, step, host_tree)
        return SaveHandle(future, step, block)

    def save_sync(self, step: int, tree: Any) -> dict:
        """Snapshot + persist inline (the baseline async saves beat)."""
        t0 = time.monotonic()
        with tracing.span("checkpoint.save",
                          attributes={"step": step, "shard": self.shard_id,
                                      "phase": "sync"}):
            w0 = time.time()
            host_tree = snapshot_to_host(tree)
            self._ledger_snapshot(host_tree, w0, time.time())
            manifest = self._persist(step, host_tree)
        ckpt_metrics.SAVE_BLOCK_SECONDS.observe(time.monotonic() - t0,
                                                tags={"mode": "sync"})
        return manifest

    @staticmethod
    def _ledger_snapshot(host_tree: Any, start: float, end: float) -> None:
        """Ledger one device->host snapshot and stage its bytes in the
        ``ckpt_staging`` pool (released when the persist drops the host
        copy)."""
        dt = _telemetry()
        if dt is None:
            return
        nbytes = dt.tree_nbytes(host_tree)
        dt.record_transfer("d2h", nbytes, src="ckpt_snapshot",
                           start=start, end=end)
        dt.pool_add("ckpt_staging", nbytes)

    # ------------------------------------------------------------ persist
    def _persist(self, step: int, host_tree: Any) -> dict:
        try:
            return self._persist_inner(step, host_tree)
        finally:
            # The host staging copy dies with this frame — release its
            # pool bytes whether the persist committed, failed, or the
            # writer was aborted before it started.
            dt = _telemetry()
            if dt is not None:
                dt.pool_sub("ckpt_staging", dt.tree_nbytes(host_tree))

    def _persist_inner(self, step: int, host_tree: Any) -> dict:
        if self._aborted.is_set():
            raise RuntimeError("shard writer aborted")
        t0 = time.monotonic()
        try:
            with tracing.span("checkpoint.save",
                              attributes={"step": step, "shard": self.shard_id,
                                          "phase": "persist"}):
                fault_injection.check("ckpt_shard_write")
                doc, skeleton, kind, arrays = layout.build_shard(
                    host_tree, self.shard_id, self.world_size)
                tmp = _invoke(self.coordinator, "begin_save", step,
                              self.world_size, self.epoch)
                manifest = layout.write_shard(tmp, self.shard_id, doc,
                                              skeleton, kind, arrays, step)
                ckpt_metrics.BYTES_WRITTEN.inc(manifest["bytes"])
                self._put_replica(step, doc, skeleton, kind, arrays)
                _invoke(self.coordinator, "shard_complete", step,
                        self.shard_id, manifest, self.epoch)
        except BaseException as e:
            try:
                _invoke(self.coordinator, "shard_failed", step, self.shard_id,
                        repr(e), self.epoch)
            except Exception:
                pass
            logger.warning("checkpoint shard %s step %s failed: %r",
                           self.shard_id, step, e)
            raise
        ckpt_metrics.SAVE_SECONDS.observe(time.monotonic() - t0)
        return manifest

    def _put_replica(self, step: int, doc, skeleton, kind, arrays) -> None:
        if not self.replicate:
            return
        import ray_tpu

        if not ray_tpu.is_initialized():
            return
        try:
            payload = {"doc": doc, "skeleton": skeleton, "kind": kind,
                       "arrays": arrays, "shard_id": self.shard_id,
                       "step": step}
            ref = ray_tpu.put(payload)
            _invoke(self.coordinator, "put_replica", step, self.shard_id,
                    {"ref": ref})
        except Exception as e:  # replica tier is best-effort
            logger.debug("replica put failed for step %s shard %s: %r",
                         step, self.shard_id, e)

    # ---------------------------------------------------------- lifecycle
    def drain(self, timeout: Optional[float] = 60.0) -> None:
        """Wait until every queued persist has finished (commit included).
        Failures of individual saves do not raise here — the next commit
        supersedes them; inspect SaveHandles for per-save outcomes."""
        self._exec.submit(lambda: None).result(timeout)

    def abort(self) -> None:
        """Tear down: queued-but-unstarted persists become no-ops.  The
        persist already in flight (if any) may still complete — committing
        a fully written step is never wrong."""
        self._aborted.set()

    def close(self) -> None:
        self.abort()
        self._exec.shutdown(wait=False)
