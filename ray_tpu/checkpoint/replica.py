"""In-memory peer replica tier (Gemini, SOSP '23 §4).

The coordinator already pins each shard snapshot in the object store; the
piece that survives a *node* failure is the ``ReplicaHolder`` — an actor
scheduled onto a different node than the writers that materializes its
own copy of every registered shard payload.  Recovery then reads from
whichever tier is still alive instead of walking back to (slow, possibly
remote) checkpoint storage.

On a single-node cluster there is no peer to place the holder on;
``start_peer_holder`` returns None and the tier degrades to the object
store copy alone — still enough for worker-death (not node-death)
recovery, which is what the single-host tests exercise.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class ReplicaHolder:
    """Holds materialized shard payloads: (step, shard_id) -> payload.

    Nothing pins this actor to a single-threaded mailbox (tests also use
    the class directly), so ``_shards`` is lock-protected; the (slow, up
    to 30s) payload materialization happens *before* taking the lock so a
    wedged fetch from a dying writer node can't stall every other call.
    """

    def __init__(self):
        self._shards: Dict[tuple, dict] = {}  # guarded_by: _lock
        self._lock = threading.Lock()

    def hold(self, step: int, shard_id: int, wrapped_ref: dict) -> None:
        import ray_tpu

        # Materialize NOW: the point is a copy that outlives the writer's
        # node, not another pointer into its object store.  Bounded: if
        # the writer's node died between register and mirror, fail this
        # mirror (the coordinator tolerates it) instead of wedging the
        # holder's mailbox.
        payload = ray_tpu.get(wrapped_ref["ref"], timeout=30)
        with self._lock:
            self._shards[(step, shard_id)] = payload

    def trim(self, keep_steps: List[int]) -> None:
        keep = set(keep_steps)
        with self._lock:
            for key in [k for k in self._shards if k[0] not in keep]:
                del self._shards[key]

    def fetch(self, step: int) -> Dict[int, dict]:
        """All held shard payloads for a step (possibly partial)."""
        with self._lock:
            return {sid: p for (s, sid), p in self._shards.items()
                    if s == step}

    def held(self) -> List[tuple]:
        with self._lock:
            return sorted(self._shards)


def _pick_peer_node() -> Optional[str]:
    """A live node other than this one (head, where the coordinator runs
    by default), preferring the node hosting the fewest live actors: a
    holder colocated with a train worker dies in the very preemption it
    exists to survive, so spread away from the busy worker nodes.  None
    on single-node clusters."""
    from ray_tpu._private.runtime import get_runtime

    runtime = get_runtime()
    head = str(runtime.head_node_id)
    load: Dict[str, int] = {}
    for st in list(runtime._actors.values()):
        if st.state == "ALIVE" and st.node_id is not None:
            nid = str(st.node_id)
            load[nid] = load.get(nid, 0) + 1
    candidates = [str(n.id) for n in runtime.scheduler.nodes()
                  if n.alive and str(n.id) != head]
    if not candidates:
        return None
    return min(candidates, key=lambda nid: load.get(nid, 0))


def start_peer_holder():
    """Start a ReplicaHolder on a peer node, or return None when the
    cluster has nowhere else to put it."""
    import ray_tpu
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    node_id = _pick_peer_node()
    if node_id is None:
        return None
    return (ray_tpu.remote(ReplicaHolder)
            .options(scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id, soft=True))
            .remote())
